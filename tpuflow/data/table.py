"""Versioned Parquet table store — the Delta Lake / Spark-table equivalent (N6-N7).

The reference persists image data as Delta tables in a per-user database
(bronze/silver medallion, P1/01_data_prep.py:84-95,136,216-222). This is
the native equivalent: a database is a directory, a table is a directory
of immutable versions, each version a set of Parquet part files plus a
JSON manifest. Semantics kept from the reference:

- overwrite writes a NEW version and atomically repoints ``_latest``
  (Delta's versioned overwrite);
- binary (image) columns can be stored uncompressed — the reference
  disables compression for binary reads' sake (P1/01:91-92);
- tables are addressed ``database.table`` like ``spark.table(...)``.

No SQL engine: only the operations the workshop exercises (SURVEY.md N6).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

_MANIFEST = "_manifest.json"
_LATEST = "_latest"

# Concurrent appends (e.g. every process of a multi-host batch-
# inference job writing its shard into one predictions table) must not
# both claim ``latest_version()+1`` — each commit runs under this lock
# so versions are allocated one writer at a time.
from tpuflow.core.locks import dir_lock as _table_lock  # noqa: E402


@dataclass
class TableVersion:
    version: int
    path: str
    num_rows: int
    files: List[str]
    created_at: float
    schema: List[str]


class Table:
    """Handle to one versioned table directory."""

    def __init__(self, path: str, name: str):
        self.path = path
        self.name = name

    # ---- write ----------------------------------------------------------

    def write(
        self,
        data: pa.Table,
        mode: str = "overwrite",
        compression: Optional[str] = "zstd",
        rows_per_file: int = 512,
    ) -> TableVersion:
        """Write a new version. ``compression=None`` stores uncompressed
        (use for binary image columns, ≙ P1/01:91-92).

        ``append`` writes ONLY the new rows; the new version's manifest
        references the previous version's part files (Delta-style
        incremental commit), so k appends cost O(new rows), not O(total).
        """
        if mode not in ("overwrite", "append"):
            raise ValueError(f"unknown write mode {mode!r}")
        with _table_lock(self.path):
            return self._write_locked(data, mode, compression, rows_per_file)

    def _write_locked(
        self,
        data: pa.Table,
        mode: str,
        compression: Optional[str],
        rows_per_file: int,
    ) -> TableVersion:
        prev_files: List[str] = []
        prev_rows = 0
        if mode == "append" and self.exists():
            prev = self.manifest()
            if list(data.schema.names) != list(prev.schema):
                # Delta-style schema enforcement: reject rather than write
                # parts that cannot be concatenated at read time
                raise ValueError(
                    f"append schema {data.schema.names} != table schema "
                    f"{prev.schema}"
                )
            # normalize to table-root-relative paths
            prev_files = [
                f if "/" in f else f"v{prev.version}/{f}" for f in prev.files
            ]
            prev_rows = prev.num_rows
        version = self.latest_version() + 1 if self.exists() else 0
        vdir = os.path.join(self.path, f"v{version}")
        os.makedirs(vdir, exist_ok=True)
        files = list(prev_files)
        n = data.num_rows
        codec = compression if compression is not None else "none"
        for i, start in enumerate(range(0, max(n, 1), rows_per_file)):
            chunk = data.slice(start, rows_per_file)
            fname = f"part-{i:05d}.parquet"
            pq.write_table(chunk, os.path.join(vdir, fname), compression=codec)
            files.append(f"v{version}/{fname}")
        manifest = TableVersion(
            version=version,
            path=vdir,
            num_rows=prev_rows + n,
            files=files,
            created_at=time.time(),
            schema=data.schema.names,
        )
        with open(os.path.join(vdir, _MANIFEST), "w") as f:
            json.dump(manifest.__dict__, f)
        # atomic repoint of _latest
        fd, tmp = tempfile.mkstemp(dir=self.path)
        with os.fdopen(fd, "w") as f:
            f.write(str(version))
        os.replace(tmp, os.path.join(self.path, _LATEST))
        return manifest

    def ensure(self, schema: pa.Schema) -> None:
        """Create the table as an empty v0 with ``schema`` iff it does
        not exist yet — atomically (check + create under the table
        lock), so concurrent writers can't clobber a sibling's data
        with an empty overwrite."""
        with _table_lock(self.path):
            if not self.exists():
                self._write_locked(
                    schema.empty_table(), "overwrite", None, 512
                )

    # ---- read -----------------------------------------------------------

    def exists(self) -> bool:
        return os.path.exists(os.path.join(self.path, _LATEST))

    def latest_version(self) -> int:
        with open(os.path.join(self.path, _LATEST)) as f:
            return int(f.read().strip())

    def versions(self) -> List[int]:
        out = []
        for d in os.listdir(self.path):
            if d.startswith("v") and d[1:].isdigit():
                out.append(int(d[1:]))
        return sorted(out)

    def manifest(self, version: Optional[int] = None) -> TableVersion:
        version = self.latest_version() if version is None else version
        with open(os.path.join(self.path, f"v{version}", _MANIFEST)) as f:
            return TableVersion(**json.load(f))

    def files(self, version: Optional[int] = None) -> List[str]:
        m = self.manifest(version)
        # table-root-relative entries ("vN/part-x") vs legacy bare names
        return [
            os.path.join(self.path, f) if "/" in f else os.path.join(m.path, f)
            for f in m.files
        ]

    def read(
        self,
        columns: Optional[Sequence[str]] = None,
        version: Optional[int] = None,
    ) -> pa.Table:
        paths = self.files(version)
        tables = [pq.read_table(p, columns=list(columns) if columns else None) for p in paths]
        return pa.concat_tables(tables)

    def count(self, version: Optional[int] = None) -> int:
        return self.manifest(version).num_rows

    def schema(self, version: Optional[int] = None) -> pa.Schema:
        return pq.read_schema(self.files(version)[0])

    def iter_batches(
        self,
        columns: Optional[Sequence[str]] = None,
        batch_size: int = 256,
        version: Optional[int] = None,
    ) -> Iterator[pa.RecordBatch]:
        for p in self.files(version):
            pf = pq.ParquetFile(p)
            yield from pf.iter_batches(
                batch_size=batch_size, columns=list(columns) if columns else None
            )

    def to_pandas(self, columns: Optional[Sequence[str]] = None, version=None):
        return self.read(columns, version).to_pandas()

    def delete(self) -> None:
        shutil.rmtree(self.path, ignore_errors=True)


class TableStore:
    """A 'database' of tables rooted at one directory (≙ per-user Spark DB,
    P1/00_setup.py:3-11 + P1/01:84-87)."""

    def __init__(self, root: str, database: str = "default"):
        self.root = root
        self.database = database
        self.db_path = os.path.join(root, database)
        os.makedirs(self.db_path, exist_ok=True)

    def table(self, name: str) -> Table:
        if "." in name:  # database.table addressing, ≙ spark.table("db.tbl")
            db, name = name.split(".", 1)
            return TableStore(self.root, db).table(name)
        return Table(os.path.join(self.db_path, name), name)

    def tables(self) -> List[str]:
        return sorted(
            d
            for d in os.listdir(self.db_path)
            if os.path.isdir(os.path.join(self.db_path, d))
        )

    def drop_database(self) -> None:
        """≙ DROP DATABASE ... CASCADE (P1/01:84-86)."""
        shutil.rmtree(self.db_path, ignore_errors=True)
        os.makedirs(self.db_path, exist_ok=True)


def table_from_pydict(d: Dict[str, list]) -> pa.Table:
    return pa.table(d)
