"""Fused linear + cross-entropy over a vocab-chunked scan.

The LM loss is the last large-tensor sink in a decoder training step:
``logits = hidden @ W`` materializes a ``(B·S, vocab)`` float32 tensor
(2.1 GB at the bench shapes) that XLA writes, reads for log-softmax,
keeps as a backward residual, and touches again for ``dlogits`` — all
HBM traffic that never needed to exist, because cross-entropy only
needs one online logsumexp and one gathered target logit per row.

:func:`fused_linear_token_loss` computes the SAME mean cross-entropy
as ``token_loss(lm_head_dot(hidden, W), targets)`` (mask,
ignore_index, label smoothing included) without ever materializing the
full logits: the forward scans vocab chunks keeping a running
(max, normalizer, target-logit, logit-sum) per row — the flash-
attention trick applied to the classifier axis — and the custom-VJP
backward rebuilds each chunk's logits from the saved ``(hidden, lse)``
to form ``softmax - onehot`` chunk by chunk, accumulating ``dhidden``
and ``dW`` with bf16 MXU dots. Peak extra memory is one
``(rows, vocab_chunk)`` tile instead of the whole logits tensor.

The matmuls run in the ACTIVATION dtype with float32 accumulation
(tpuflow.models.transformer.lm_head_dot convention — full-rate MXU for
bf16 models); every reduction is float32. The reference has no
language-model surface at all (SURVEY.md §2c); this backs the
beyond-reference LM family's loss path.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_NEG_BIG = -1e30


class _Cfg(NamedTuple):
    vocab: int          # true vocab size (kernel may be padded past it)
    chunk: int          # vocab chunk width (padded vocab divides by it)
    label_smoothing: float


def _chunked_kernel(kernel, cfg: _Cfg):
    """(D, V) -> (n_chunks, D, chunk), zero-padding the vocab axis."""
    d, v = kernel.shape
    pad = (-v) % cfg.chunk
    if pad:
        kernel = jnp.pad(kernel, ((0, 0), (0, pad)))
    n = (v + pad) // cfg.chunk
    return kernel.reshape(d, n, cfg.chunk).transpose(1, 0, 2)


def _fwd_scan(cfg: _Cfg, hidden, kernel, targets):
    """Online-logsumexp pass. Returns (lse, target_logit, logit_sum),
    all float32 of shape (rows,)."""
    rows = hidden.shape[0]
    wc = _chunked_kernel(kernel, cfg)

    def step(carry, xs):
        m, s, tl, tot = carry
        ci, w_c = xs
        base = ci * cfg.chunk
        logits = lax.dot_general(
            hidden, w_c.astype(hidden.dtype),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        col_ok = base + jnp.arange(cfg.chunk) < cfg.vocab
        masked = jnp.where(col_ok[None, :], logits, _NEG_BIG)
        m_new = jnp.maximum(m, jnp.max(masked, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.where(col_ok[None, :], jnp.exp(masked - m_new[:, None]),
                      0.0),
            axis=-1,
        )
        tot = tot + jnp.sum(
            jnp.where(col_ok[None, :], logits, 0.0), axis=-1
        )
        off = targets - base
        in_c = (off >= 0) & (off < cfg.chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(off, 0, cfg.chunk - 1)[:, None], axis=1
        )[:, 0]
        tl = tl + jnp.where(in_c, picked, 0.0)
        return (m_new, s, tl, tot), None

    n = wc.shape[0]
    init = (
        jnp.full((rows,), _NEG_BIG, jnp.float32),
        jnp.zeros((rows,), jnp.float32),
        jnp.zeros((rows,), jnp.float32),
        jnp.zeros((rows,), jnp.float32),
    )
    (m, s, tl, tot), _ = lax.scan(step, init, (jnp.arange(n), wc))
    lse = m + jnp.log(jnp.maximum(s, 1e-37))
    return lse, tl, tot


def _loss_from_stats(cfg: _Cfg, lse, tl, tot, valid):
    nll_t = lse - tl
    nll_u = lse - tot / cfg.vocab
    eps = cfg.label_smoothing
    losses = (1.0 - eps) * nll_t + eps * nll_u
    return jnp.sum(losses * valid) / jnp.maximum(jnp.sum(valid), 1.0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _fused_core(cfg: _Cfg, hidden, kernel, targets, valid):
    lse, tl, tot = _fwd_scan(cfg, hidden, kernel, targets)
    return _loss_from_stats(cfg, lse, tl, tot, valid)


def _fused_core_fwd(cfg: _Cfg, hidden, kernel, targets, valid):
    lse, tl, tot = _fwd_scan(cfg, hidden, kernel, targets)
    loss = _loss_from_stats(cfg, lse, tl, tot, valid)
    return loss, (hidden, kernel, targets, valid, lse)


def _fused_core_bwd(cfg: _Cfg, res, g):
    hidden, kernel, targets, valid, lse = res
    rows = hidden.shape[0]
    wc = _chunked_kernel(kernel, cfg)
    eps = cfg.label_smoothing
    # d(loss)/d(logit[r, v]) = w_r * (softmax - (1-eps)*onehot - eps/V)
    w = g * valid / jnp.maximum(jnp.sum(valid), 1.0)

    def step(dh, xs):
        ci, w_c = xs
        base = ci * cfg.chunk
        logits = lax.dot_general(
            hidden, w_c.astype(hidden.dtype),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        col_ok = base + jnp.arange(cfg.chunk) < cfg.vocab
        p = jnp.where(
            col_ok[None, :], jnp.exp(logits - lse[:, None]), 0.0
        )
        off = targets - base
        in_c = (off >= 0) & (off < cfg.chunk)
        onehot = (
            jnp.arange(cfg.chunk)[None, :]
            == jnp.clip(off, 0, cfg.chunk - 1)[:, None]
        ) & in_c[:, None]
        d = p - (1.0 - eps) * onehot - jnp.where(
            col_ok[None, :], eps / cfg.vocab, 0.0
        )
        dl = (d * w[:, None]).astype(hidden.dtype)
        dh = dh + lax.dot_general(
            dl, w_c.astype(hidden.dtype),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dw_c = lax.dot_general(
            hidden, dl, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dh, dw_c

    n = wc.shape[0]
    dh, dw_chunks = lax.scan(
        step, jnp.zeros(hidden.shape, jnp.float32), (jnp.arange(n), wc)
    )
    d_v = kernel.shape[1]
    dw = dw_chunks.transpose(1, 0, 2).reshape(kernel.shape[0], -1)
    dw = dw[:, :d_v].astype(kernel.dtype)
    ct_int = np.zeros(targets.shape, jax.dtypes.float0)
    return dh.astype(hidden.dtype), dw, ct_int, jnp.zeros_like(valid)


_fused_core.defvjp(_fused_core_fwd, _fused_core_bwd)


def fused_linear_token_loss(
    hidden,
    kernel,
    targets,
    mask=None,
    ignore_index: int = -1,
    label_smoothing: float = 0.0,
    vocab_chunk: int = 8192,
):
    """Mean cross-entropy of ``(hidden @ kernel)[i]`` predicting
    ``targets[i]`` — identical semantics to
    ``token_loss(lm_head_dot(hidden, kernel), targets, ...)``
    (tpuflow.models.transformer) — WITHOUT materializing the logits.

    ``hidden``: (..., D) activations; ``kernel``: (D, vocab);
    ``targets``: (...) int32 (same leading shape as hidden); ``mask``
    broadcastable to targets. Differentiable w.r.t. hidden and kernel.
    The caller applies any next-token shift (as with token_loss).

    Targets outside ``[0, vocab)`` are folded into the ignore mask
    (contribute zero loss and zero gradient) rather than silently
    picking a padded-column logit — corrupt data must not give a
    DIFFERENT wrong answer here than in the unfused ``token_loss``
    path (ADVICE r03).
    """
    if not 0.0 <= label_smoothing < 1.0:
        raise ValueError(
            f"label_smoothing must be in [0, 1), got {label_smoothing}"
        )
    if hidden.shape[:-1] != targets.shape:
        raise ValueError(
            f"hidden rows {hidden.shape[:-1]} != targets {targets.shape}"
        )
    d = hidden.shape[-1]
    vocab = kernel.shape[1]
    if kernel.shape[0] != d:
        raise ValueError(
            f"kernel {kernel.shape} does not match hidden dim {d}"
        )
    rows_shape = targets.shape
    h2 = hidden.reshape(-1, d)
    t2 = targets.reshape(-1)
    in_range = (t2 >= 0) & (t2 < vocab)
    valid = ((t2 != ignore_index) & in_range).astype(jnp.float32)
    if mask is not None:
        valid = valid * jnp.broadcast_to(
            mask, rows_shape
        ).reshape(-1).astype(jnp.float32)
    t2 = jnp.where(in_range & (t2 != ignore_index), t2, 0)
    cfg = _Cfg(
        vocab=vocab,
        chunk=min(int(vocab_chunk), max(128, vocab)),
        label_smoothing=float(label_smoothing),
    )
    return _fused_core(cfg, h2, kernel, t2, valid)
