"""Blockwise flash attention as a Pallas TPU kernel.

Online-softmax forward (running max / normalizer, O(S) memory) and a
recomputation backward (two kernels: dQ over query blocks, dK/dV over
key blocks) wrapped in ``jax.custom_vjp``. This is the hot op of the
attention model family and the per-shard compute of ring attention
(tpuflow.parallel.ring_attention); the reference has no attention
anywhere (SURVEY.md §2c, §5.7) — this is the long-context capability
the TPU build adds as first-class.

Layout: ``(batch, heads, seq, head_dim)``. The MXU dots run in the
INPUT dtype (bf16 in → bf16×bf16 with float32 accumulation — the
full-rate MXU mode; casting operands to f32 first would drop to the
~8x-slower f32 path, measured round 2 as a ~2 TFLOP/s kernel), and all
softmax statistics (max / normalizer / lse) are float32. Outputs match
the input dtype.

K/V STREAM through the grid: every kernel walks a
``(batch·heads, outer, inner)`` grid whose inner dimension revolves a
``block_k`` (resp. ``block_q``) VMEM window over the sequence, with the
online-softmax / gradient state carried across inner steps in VMEM
scratch accumulators. Pallas double-buffers the revolving window, so
the HBM→VMEM copy of tile *t+1* overlaps the MXU work on tile *t*, and
per-(batch, head) VMEM is O(block · head_dim) — independent of
sequence length. A 64k-token forward at head_dim 128 needs ~16 MB of
K+V per (batch, head) whole (beyond VMEM); streamed it needs two
32 KB tiles in flight. Causally-masked (block_q, block_k) pairs are
skipped with ``pl.when`` (~2x at long sequence).

On non-TPU backends the kernels run in Pallas interpret mode, so the
whole test suite exercises the real kernel code on CPU (SURVEY.md §4's
world-size-1/CPU-backend discipline).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpuflow.core.compat import shape_dtype_struct as _sds
from tpuflow.core.compat import tpu_compiler_params as _tpu_compiler_params
from tpuflow.core.compat import typeof as _typeof

_NEG_BIG = -1e30


class _Cfg(NamedTuple):
    """Static kernel configuration (hashable → custom_vjp nondiff arg).

    ``causal_shift`` offsets the causal diagonal: visible iff
    ``col <= row + causal_shift``. 0 is the standard mask; -1 is the
    STRICT mask (col < row) that striped ring attention needs for
    visits from later-striped shards (tpuflow.parallel.ring_attention).

    ``window`` (sliding-window / local attention, requires ``causal``):
    additionally visible iff ``col > row + causal_shift - window`` —
    each query sees at most its last ``window`` keys (itself included),
    and the kernels SKIP key/query blocks wholly outside the band, so
    compute is O(S·window) instead of O(S²/2).
    """

    causal: bool
    scale: float
    block_q: int
    block_k: int
    sq_valid: int  # unpadded query length
    skv_valid: int  # unpadded key/value length
    interpret: bool
    causal_shift: int = 0
    window: Optional[int] = None
    # sequence packing: a (BH, 1, S) segment-id row rides as an extra
    # kernel input and positions attend only within their own segment
    has_segments: bool = False
    # grouped-query attention: q carries kv_group times more heads than
    # k/v; the kernels read K/V blocks at head index b // kv_group (an
    # index-map remap — K/V are NEVER materialized expanded), and the
    # dK/dV kernel's inner grid enumerates (group member, q block) so
    # the per-KV-head gradient accumulates across its whole group
    kv_group: int = 1
    # batched-bh: each grid cell processes bh_block (batch·head) rows
    # (an unrolled static loop over G sub-dots sharing one mask
    # computation and one revolving-window DMA per cell). At short
    # sequence the inner grid is tiny (s=1024 @ 512-blocks → 2×2) and
    # per-grid-cell overhead (window-swap DMA setup + scalar control)
    # dominates the MXU work — batching bh cuts the cell count G× at
    # identical FLOPs. Under GQA, G must be a multiple of kv_group:
    # the cell's K/V block then carries G/group rows, row gi reads
    # gi//group, and the dK/dV kernel runs the group sweep in-kernel.
    # G=1 is exactly the classic kernel.
    bh_block: int = 1


def _vma(*xs):
    """Union of the inputs' varying-manual-axes sets, so kernel outputs
    carry the right vma when called under shard_map (e.g. from ring
    attention) and an empty set otherwise."""
    out = frozenset()
    for x in xs:
        out = out | getattr(_typeof(x), "vma", frozenset())
    return out


def _static_scale(scale, head_dim: int) -> float:
    """Resolve the softmax scale to a STATIC Python float. Both
    attention impls carry scale as a nondiff/static argument (it bakes
    into the kernel config / custom-vjp closure), so a traced jnp
    scalar cannot flow here — fail with a clear contract error instead
    of jax's ConcretizationTypeError deep in float()."""
    if scale is None:
        return head_dim ** -0.5
    try:
        return float(scale)
    except (jax.errors.ConcretizationTypeError,
            jax.errors.TracerArrayConversionError) as e:
        # only the concreteness failures get the contract message —
        # a genuinely malformed scale (multi-element array, a string)
        # surfaces as its own TypeError/ValueError undisturbed
        raise TypeError(
            "scale must be a static Python number (it is a non-"
            "differentiable static argument baked into the attention "
            "config); got a traced/abstract value — hoist it out of "
            "jit or pass a concrete float"
        ) from e


def pick_attn_impl(seq_len: int, requested: str = "auto") -> str:
    """Resolve an ``attn_impl`` request. ``'auto'`` chooses ``'flash'``
    on a TPU backend once the sequence is long enough that avoiding the
    materialized O(S²) score matrix pays for the kernel's blockwise
    bookkeeping (vision-length sequences are faster as one fused XLA
    einsum chain), ``'einsum'`` otherwise. Explicit requests pass
    through untouched."""
    if requested != "auto":
        return requested
    from tpuflow.core.hw import is_tpu_backend

    return "flash" if (seq_len >= 1024 and is_tpu_backend()) else "einsum"


def mha_reference(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """Plain-XLA multi-head attention (numerics oracle for the kernel).

    Everything float32 — use :func:`mha_xla` in production models."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s * scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _mha_mask(causal: bool, window, sq: int, sk: int, segs=None):
    """(sq, sk) static band mask (None if unmasked), plus the optional
    batched segment mask (B, 1, sq, sk): positions attend only within
    their own packed document."""
    mask = None
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        if window is not None:
            mask = mask & jnp.triu(
                jnp.ones((sq, sk), bool), k=sk - sq - window + 1
            )
    if segs is not None:
        seg_mask = (segs[:, None, :, None] == segs[:, None, None, :])
        mask = seg_mask if mask is None else (mask & seg_mask)
    return mask


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _mha_xla_core(q, k, v, segs, causal: bool, scale: float, window):
    o, _ = _mha_xla_fwd_impl(q, k, v, segs, causal, scale, window)
    return o


def _mha_xla_fwd_impl(q, k, v, segs, causal, scale, window):
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    mask = _mha_mask(causal, window, q.shape[2], k.shape[2], segs)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_BIG)
    m = jnp.max(s, axis=-1, keepdims=True)
    lse = m + jnp.log(jnp.sum(jnp.exp(s - m), axis=-1, keepdims=True))
    p = jnp.exp(s - lse)
    o = jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(q.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)
    return o, lse[..., 0]


def _mha_xla_fwd(q, k, v, segs, causal, scale, window):
    o, lse = _mha_xla_fwd_impl(q, k, v, segs, causal, scale, window)
    return o, (q, k, v, segs, o, lse)


def _mha_xla_bwd(causal, scale, window, res, do):
    # custom backward with the SAME dtype discipline as the Pallas
    # kernels: rebuild probabilities from the saved lse and cast p/ds
    # to the input dtype before every einsum. Autodiff through the f32
    # softmax would make the cotangent of the scores f32 and push the
    # four O(S^2) backward dots onto the slow f32 MXU path — the exact
    # leak the module docstring promises not to have.
    q, k, v, segs, o, lse = res
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)[..., None]
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    mask = _mha_mask(causal, window, q.shape[2], k.shape[2], segs)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_BIG)
    p = jnp.exp(s - lse[..., None])
    pb = p.astype(q.dtype)
    dv = jnp.einsum("bhqk,bhqd->bhkd", pb, do,
                    preferred_element_type=jnp.float32).astype(v.dtype)
    dp = jnp.einsum("bhqd,bhkd->bhqk", do, v,
                    preferred_element_type=jnp.float32)
    ds = (p * (dp - delta) * scale).astype(q.dtype)
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k,
                    preferred_element_type=jnp.float32).astype(q.dtype)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q,
                    preferred_element_type=jnp.float32).astype(k.dtype)
    d_segs = (None if segs is None
              else np.zeros(segs.shape, jax.dtypes.float0))
    return dq, dk, dv, d_segs


_mha_xla_core.defvjp(_mha_xla_fwd, _mha_xla_bwd)


def mha_xla(q, k, v, causal: bool = False, scale: Optional[float] = None,
            window: Optional[int] = None, segment_ids=None):
    """Production XLA attention: einsums in the INPUT dtype with float32
    accumulation (full-rate MXU for bf16 models — upcasting operands to
    f32 first, as the oracle does, lands on the ~8x-slower f32 MXU
    path), float32 softmax — in the FORWARD and, via a custom VJP
    mirroring the flash kernels' backward, in every O(S^2) BACKWARD dot
    too (autodiff through an f32 softmax would silently run them
    f32×f32). The right impl for short sequences where the score matrix
    fits comfortably (vision models); long sequences go to
    :func:`flash_attention`. ``window`` applies the same sliding-window
    mask as the kernel (no block skipping here — at einsum lengths the
    full score matrix is already materialized). ``segment_ids``
    (B, S) int32 masks attention to WITHIN each packed document —
    sequence-packing correctness (positions never attend across the
    documents sharing their training row)."""
    if window is not None:
        # same contract as flash_attention — swapping impls via
        # pick_attn_impl must not change error behavior
        if not causal:
            raise ValueError("window (sliding-window attention) requires "
                             "causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if segment_ids is not None and segment_ids.shape != (
            q.shape[0], q.shape[2]):
        raise ValueError(
            f"segment_ids must be (batch, seq)={q.shape[0], q.shape[2]}, "
            f"got {segment_ids.shape}"
        )
    scale = _static_scale(scale, q.shape[-1])
    return _mha_xla_core(q, k, v, segment_ids, causal, scale, window)


# ---------------------------------------------------------------------------
# masked block-attention reference (jnp)
#
# Same masking semantics as the kernels, on (BH, S, D) arrays. Used as
# the numerics oracle in tests AND as the interpret-mode block compute
# of ring attention: Pallas's HLO interpreter cannot evaluate kernels
# whose operands carry varying manual axes (shard_map vma), so off-TPU
# the ring path runs this math instead — the kernels are equivalence-
# tested against it in tests/test_ops.py.
# ---------------------------------------------------------------------------


def _mask_for(cfg: _Cfg, sq: int, skv: int):
    row = lax.broadcasted_iota(jnp.int32, (sq, skv), 0)
    col = lax.broadcasted_iota(jnp.int32, (sq, skv), 1)
    mask = (col < cfg.skv_valid) & (row < cfg.sq_valid)
    if cfg.causal:
        mask = mask & (col <= row + cfg.causal_shift)
        if cfg.window is not None:
            mask = mask & (col > row + cfg.causal_shift - cfg.window)
    return mask


def _fwd_ref(cfg: _Cfg, q, k, v):
    """(o, lse) with the kernel's masking; fully-masked rows → o=0,
    lse=_NEG_BIG (the ring-merge identity)."""
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * cfg.scale
    mask = _mask_for(cfg, q.shape[1], k.shape[1])
    m = jnp.max(jnp.where(mask, s, _NEG_BIG), axis=-1, keepdims=True)
    p = jnp.where(mask, jnp.exp(s - m), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    safe = jnp.where(l > 0, l, 1.0)
    o = jnp.where(l > 0, jnp.einsum("bqk,bkd->bqd", p, vf) / safe, 0.0)
    lse = jnp.where(l[..., 0] > 0, m[..., 0] + jnp.log(safe[..., 0]), _NEG_BIG)
    return o.astype(q.dtype), lse


def _bwd_ref(cfg: _Cfg, q, k, v, o, lse, do):
    """Flash-attention backward in plain jnp (global-lse probabilities)."""
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)[..., None]
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * cfg.scale
    mask = _mask_for(cfg, q.shape[1], k.shape[1])
    p = jnp.where(mask, jnp.exp(s - lse[..., None]), 0.0)
    dv = jnp.einsum("bqk,bqd->bkd", p, dof)
    dp = jnp.einsum("bqd,bkd->bqk", dof, vf)
    ds = p * (dp - delta)
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf) * cfg.scale
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf) * cfg.scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


# Softmax-statistic scratch rows are lane-replicated to the TPU lane
# width: a (block_q, 1) f32 VMEM buffer would occupy a full (bq, 128)
# tile anyway, and whole-tile stores avoid sub-lane masking.
_LANES = 128


def _causal_last_j(qi: int, bq: int, bk: int, nk: int, shift: int = 0):
    """Index of the LAST key block any row of query block ``qi`` can
    see under the causal mask col <= row + shift (the inner grid skips
    blocks beyond it). Clamped at 0 so a fully-masked first block
    (possible with shift < 0) still takes the init/finalize path."""
    last_col = (qi + 1) * bq - 1 + shift
    return jnp.clip(lax.div(last_col, bk), 0, nk - 1)


def _window_first_j(qi: int, bq: int, bk: int, nk: int, shift: int,
                    window: int):
    """Index of the FIRST key block any row of query block ``qi`` can
    see under the sliding window col > row + shift - window (the inner
    grid skips earlier blocks — this is what makes local attention
    O(S·window))."""
    first_col = qi * bq + shift - window + 1
    return jnp.clip(lax.div(first_col, bk), 0, nk - 1)


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, cfg: _Cfg):
    if cfg.has_segments:
        seg_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, lse_ref, m_ref, l_ref, acc_ref = rest
        seg_ref = None
    # lse_ref block is the FULL padded row, shape (1, 1, sq_pad): TPU
    # block specs require the last two block dims divisible by (8, 128)
    # or equal to the array dims — a (1, block_q) tile of a (BH, S)
    # array violates that (Mosaic rejects it on hardware even though
    # interpret mode accepts it), while a whole-row block is always
    # legal and costs only S*4 bytes of VMEM.
    bq, d = q_ref.shape[1], q_ref.shape[2]
    bk = k_ref.shape[1]
    qi = pl.program_id(1)
    j = pl.program_id(2)  # inner: revolving K/V window, sequential
    nk = pl.num_programs(2)
    G = cfg.bh_block  # rows per grid cell (static unrolled loop)

    last_j = (
        _causal_last_j(qi, bq, bk, nk, cfg.causal_shift)
        if cfg.causal else nk - 1
    )
    first_j = (
        _window_first_j(qi, bq, bk, nk, cfg.causal_shift, cfg.window)
        if (cfg.causal and cfg.window is not None) else 0
    )

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_BIG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when((j >= first_j) & (j <= last_j))
    def _compute():
        # band/bounds mask depends only on (qi, j) — computed ONCE and
        # shared by all G rows of the cell
        col = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        band = col < cfg.skv_valid
        if cfg.causal:
            row = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            band = band & (col <= row + cfg.causal_shift)
            if cfg.window is not None:
                band = band & (col > row + cfg.causal_shift - cfg.window)
        for gi in range(G):
            q = q_ref[gi]  # native dtype — bf16 in ⇒ full-rate MXU
            # GQA: row gi's K/V lives at gi // group within the cell's
            # K/V block (G==1: index 0 either way — the classic path)
            k_blk = k_ref[gi // cfg.kv_group]
            v_blk = v_ref[gi // cfg.kv_group]
            s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
            s = s * cfg.scale  # scale the f32 scores, not the bf16 operand
            mask = band
            if cfg.has_segments:
                qseg = seg_ref[gi, 0, pl.ds(qi * bq, bq)]
                kseg = seg_ref[gi, 0, pl.ds(j * bk, bk)]
                mask = mask & (qseg[:, None] == kseg[None, :])
            s = jnp.where(mask, s, _NEG_BIG)
            m = m_ref[gi, :, :1]
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            # explicit mask gate: a FULLY-masked row keeps m_new at the
            # -1e30 sentinel, where exp(s - m_new) = exp(0) = 1 would
            # count masked entries into l/acc (possible under
            # causal_shift < 0, whose first row sees nothing)
            p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
            alpha = jnp.exp(m - m_new)
            l_new = alpha * l_ref[gi, :, :1] + jnp.sum(
                p, axis=-1, keepdims=True
            )
            acc_ref[gi] = acc_ref[gi] * alpha + jnp.dot(
                p.astype(v_blk.dtype), v_blk,
                preferred_element_type=jnp.float32,
            )
            m_ref[gi] = jnp.broadcast_to(m_new, (bq, _LANES))
            l_ref[gi] = jnp.broadcast_to(l_new, (bq, _LANES))

    @pl.when(j == last_j)
    def _finalize():
        for gi in range(G):
            l = l_ref[gi, :, :1]
            safe_l = jnp.where(l > 0, l, 1.0)
            o_ref[gi] = jnp.where(l > 0, acc_ref[gi] / safe_l, 0.0).astype(
                o_ref.dtype
            )
            lse = jnp.where(
                l[:, 0] > 0, m_ref[gi, :, 0] + jnp.log(safe_l[:, 0]),
                _NEG_BIG,
            )
            lse_ref[gi, 0, pl.ds(qi * bq, bq)] = lse


def _fwd(cfg: _Cfg, q, k, v, segs=None):
    bh, sq, d = q.shape
    skv = k.shape[1]
    g = cfg.kv_group  # K/V head index = q-head index // g (GQA)
    G = cfg.bh_block  # (batch·head) rows per grid cell; G>1 ⇒ g | G
    # K/V blocks: G>1 carries the cell's OWN G//g kv rows at block
    # index b (q rows [bG,(b+1)G) ↔ kv rows [bG/g,(b+1)G/g)); G==1
    # keeps the classic per-row b//g remap (1-row blocks)
    Gkv = G // g if G > 1 else 1
    kv_map = (
        (lambda b, i, j: (b, j, 0)) if G > 1
        else (lambda b, i, j: (b // g, j, 0))
    )
    grid = (bh // G, sq // cfg.block_q, skv // cfg.block_k)
    in_specs = [
        pl.BlockSpec((G, cfg.block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((Gkv, cfg.block_k, d), kv_map),
        pl.BlockSpec((Gkv, cfg.block_k, d), kv_map),
    ]
    inputs = [q, k, v]
    if cfg.has_segments:
        # segment ids ride as a whole padded row, same legality
        # reasoning as the lse block (see _fwd_kernel docstring)
        in_specs.append(
            pl.BlockSpec((G, 1, segs.shape[2]), lambda b, i, j: (b, 0, 0))
        )
        inputs.append(segs)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, cfg=cfg),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((G, cfg.block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((G, 1, sq), lambda b, i, j: (b, 0, 0)),
        ],
        out_shape=[
            _sds((bh, sq, d), q.dtype, vma=_vma(q, k, v)),
            _sds((bh, 1, sq), jnp.float32, vma=_vma(q, k, v)),
        ],
        scratch_shapes=[
            pltpu.VMEM((G, cfg.block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((G, cfg.block_q, _LANES), jnp.float32),  # normalizer
            pltpu.VMEM((G, cfg.block_q, d), jnp.float32),  # output accum
        ],
        # the qi dim must stay 'arbitrary': the (1, 1, sq) lse OUTPUT
        # block's index map is invariant over qi, and a 'parallel' qi
        # would let megacore give each core a private copy of that
        # shared window — each core's flush clobbering the other's rows
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=cfg.interpret,
    )(*inputs)
    return o, lse[:, 0, :]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
               cfg: _Cfg):
    if cfg.has_segments:
        seg_ref, dq_ref, dq_acc_ref = rest
    else:
        dq_ref, dq_acc_ref = rest
        seg_ref = None
    bq, d = q_ref.shape[1], q_ref.shape[2]
    bk = k_ref.shape[1]
    qi = pl.program_id(1)
    j = pl.program_id(2)  # inner: revolving K/V window
    nk = pl.num_programs(2)
    G = cfg.bh_block

    last_j = (
        _causal_last_j(qi, bq, bk, nk, cfg.causal_shift)
        if cfg.causal else nk - 1
    )
    first_j = (
        _window_first_j(qi, bq, bk, nk, cfg.causal_shift, cfg.window)
        if (cfg.causal and cfg.window is not None) else 0
    )

    @pl.when(j == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    @pl.when((j >= first_j) & (j <= last_j))
    def _compute():
        row = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        col = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        band = (col < cfg.skv_valid) & (row < cfg.sq_valid)
        if cfg.causal:
            band = band & (col <= row + cfg.causal_shift)
            if cfg.window is not None:
                band = band & (col > row + cfg.causal_shift - cfg.window)
        for gi in range(G):
            q = q_ref[gi]
            do = do_ref[gi]
            k_blk = k_ref[gi // cfg.kv_group]
            v_blk = v_ref[gi // cfg.kv_group]
            lse = lse_ref[gi, 0, pl.ds(qi * bq, bq)][:, None]
            delta = delta_ref[gi, 0, pl.ds(qi * bq, bq)][:, None]
            s = jnp.dot(
                q, k_blk.T, preferred_element_type=jnp.float32
            ) * cfg.scale
            mask = band
            if cfg.has_segments:
                qseg = seg_ref[gi, 0, pl.ds(qi * bq, bq)]
                kseg = seg_ref[gi, 0, pl.ds(j * bk, bk)]
                mask = mask & (qseg[:, None] == kseg[None, :])
            p = jnp.where(mask, jnp.exp(s - lse), 0.0)
            dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
            ds = (p * (dp - delta)).astype(k_blk.dtype)
            dq_acc_ref[gi] = dq_acc_ref[gi] + jnp.dot(
                ds, k_blk, preferred_element_type=jnp.float32
            )

    @pl.when(j == last_j)
    def _finalize():
        for gi in range(G):
            dq_ref[gi] = (dq_acc_ref[gi] * cfg.scale).astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref, *rest,
                cfg: _Cfg):
    if cfg.has_segments:
        seg_ref, dk_ref, dv_ref, dk_acc_ref, dv_acc_ref = rest
    else:
        dk_ref, dv_ref, dk_acc_ref, dv_acc_ref = rest
        seg_ref = None
    bk, d = k_ref.shape[1], k_ref.shape[2]
    bq = q_ref.shape[1]
    ki = pl.program_id(1)
    G = cfg.bh_block
    t = pl.program_id(2)
    nt = pl.num_programs(2)
    if G > 1:
        # block path: the cell holds G//g kv rows and ALL their g
        # query-head members — the group sweep runs in-kernel, so the
        # inner grid enumerates q blocks only
        nq = nt
        i = t
    else:
        # classic per-row path: inner grid flattens (group member,
        # q block) — this key row's gradient accumulates over every
        # query head it serves (kv_group sweeps of nq q-blocks each)
        nq = nt // cfg.kv_group
        i = lax.rem(t, nq)  # q block within the current member's sweep

    # causal: the first query block whose rows can see this key block
    # (col c is visible to rows >= c - causal_shift)
    first_i = (
        jnp.clip(lax.div(ki * bk - cfg.causal_shift, bq), 0, nq - 1)
        if cfg.causal else 0
    )
    # sliding window: the LAST query block that can still see this key
    # block (row < col - causal_shift + window) — later blocks skip
    if cfg.causal and cfg.window is not None:
        last_row = ki * bk + bk - 1 - cfg.causal_shift + cfg.window - 1
        last_i = jnp.clip(lax.div(last_row, bq), 0, nq - 1)
    else:
        last_i = nq - 1

    @pl.when(t == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    @pl.when((i >= first_i) & (i <= last_i))
    def _compute():
        col = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        row = i * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        band = (col < cfg.skv_valid) & (row < cfg.sq_valid)
        if cfg.causal:
            band = band & (col <= row + cfg.causal_shift)
            if cfg.window is not None:
                band = band & (col > row + cfg.causal_shift - cfg.window)
        g = cfg.kv_group
        n_kv = (G // g) if G > 1 else 1
        for gk in range(n_kv):
            k = k_ref[gk]
            v = v_ref[gk]
            for m in range(g if G > 1 else 1):
                # q-row index within the cell: classic path has ONE q
                # row per cell (its member sweep lives in the grid);
                # block path enumerates all g members of kv row gk
                gq = gk * g + m if G > 1 else 0
                q_blk = q_ref[gq]
                do_blk = do_ref[gq]
                lse = lse_ref[gq, 0, pl.ds(i * bq, bq)][:, None]
                delta = delta_ref[gq, 0, pl.ds(i * bq, bq)][:, None]
                s = jnp.dot(
                    q_blk, k.T, preferred_element_type=jnp.float32
                ) * cfg.scale
                mask = band
                if cfg.has_segments:
                    qseg = seg_ref[gq, 0, pl.ds(i * bq, bq)]
                    kseg = seg_ref[gq, 0, pl.ds(ki * bk, bk)]
                    mask = mask & (qseg[:, None] == kseg[None, :])
                p = jnp.where(mask, jnp.exp(s - lse), 0.0)
                dv_acc_ref[gk] = dv_acc_ref[gk] + jnp.dot(
                    p.T.astype(do_blk.dtype), do_blk,
                    preferred_element_type=jnp.float32,
                )
                dp = jnp.dot(do_blk, v.T,
                             preferred_element_type=jnp.float32)
                ds = (p * (dp - delta)).astype(q_blk.dtype)
                dk_acc_ref[gk] = dk_acc_ref[gk] + jnp.dot(
                    ds.T, q_blk, preferred_element_type=jnp.float32
                )

    @pl.when(t == nt - 1)
    def _finalize():
        for gk in range((G // cfg.kv_group) if G > 1 else 1):
            dk_ref[gk] = (dk_acc_ref[gk] * cfg.scale).astype(dk_ref.dtype)
            dv_ref[gk] = dv_acc_ref[gk].astype(dv_ref.dtype)


def _bwd_impl(cfg: _Cfg, q, k, v, o, lse, do, segs=None):
    bh, sq, d = q.shape
    skv = k.shape[1]
    bh_kv = k.shape[0]  # under GQA: bh // kv_group
    g = cfg.kv_group
    G = cfg.bh_block  # G>1 ⇒ g | G (enforced in flash_attention)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    # vectors ride as (BH, 1, S) whole-row blocks — see _fwd_kernel note
    lse3 = lse[:, None, :]
    delta3 = delta[:, None, :]
    nq, nk = sq // cfg.block_q, skv // cfg.block_k
    Gkv = G // g if G > 1 else 1
    q_spec = pl.BlockSpec((G, cfg.block_q, d), lambda b, i, j: (b, i, 0))
    k_stream = pl.BlockSpec(
        (Gkv, cfg.block_k, d),
        (lambda b, i, j: (b, j, 0)) if G > 1
        else (lambda b, i, j: (b // g, j, 0)),
    )
    vec_row = pl.BlockSpec((G, 1, sq), lambda b, i, j: (b, 0, 0))
    semantics = _tpu_compiler_params(
        dimension_semantics=("parallel", "parallel", "arbitrary"),
    )

    dq_in_specs = [q_spec, k_stream, k_stream, q_spec, vec_row, vec_row]
    dq_inputs = [q, k, v, do, lse3, delta3]
    if cfg.has_segments:
        dq_in_specs.append(
            pl.BlockSpec((G, 1, segs.shape[2]), lambda b, i, j: (b, 0, 0))
        )
        dq_inputs.append(segs)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, cfg=cfg),
        grid=(bh // G, nq, nk),
        in_specs=dq_in_specs,
        out_specs=q_spec,
        out_shape=_sds((bh, sq, d), q.dtype, vma=_vma(q, k, v, do)),
        scratch_shapes=[pltpu.VMEM((G, cfg.block_q, d), jnp.float32)],
        compiler_params=semantics,
        interpret=cfg.interpret,
    )(*dq_inputs)

    # dk/dv: key blocks in the middle grid dim. Classic (G==1): the
    # innermost dim enumerates (group member, q block) so each KV
    # head's gradient accumulates over every query head it serves.
    # Block path (G>1): the cell holds G//g kv rows plus ALL their
    # members' q rows (one G-row q block), the group sweep runs
    # in-kernel, and the inner dim enumerates q blocks only.
    if G > 1:
        k_spec = pl.BlockSpec((Gkv, cfg.block_k, d),
                              lambda b, j, t: (b, j, 0))
        q_stream = pl.BlockSpec((G, cfg.block_q, d),
                                lambda b, j, t: (b, t, 0))
        vec_row_kv = pl.BlockSpec((G, 1, sq), lambda b, j, t: (b, 0, 0))
        seg_spec_kv = pl.BlockSpec(
            (G, 1, segs.shape[2]) if segs is not None else (1, 1, 1),
            lambda b, j, t: (b, 0, 0),
        )
        dkv_grid = (bh_kv // Gkv, nk, nq)
        dkv_out_lead = Gkv
    else:
        k_spec = pl.BlockSpec((1, cfg.block_k, d),
                              lambda b, j, t: (b, j, 0))
        q_stream = pl.BlockSpec(
            (1, cfg.block_q, d),
            lambda b, j, t: (b * g + t // nq, t % nq, 0),
        )
        vec_row_kv = pl.BlockSpec(
            (1, 1, sq), lambda b, j, t: (b * g + t // nq, 0, 0)
        )
        seg_spec_kv = pl.BlockSpec(
            (1, 1, segs.shape[2]) if segs is not None else (1, 1, 1),
            lambda b, j, t: (b * g, 0, 0),
        )
        dkv_grid = (bh_kv, nk, nq * g)
        dkv_out_lead = 1
    dkv_in_specs = [k_spec, k_spec, q_stream, q_stream, vec_row_kv,
                    vec_row_kv]
    dkv_inputs = [k, v, q, do, lse3, delta3]
    if cfg.has_segments:
        dkv_in_specs.append(seg_spec_kv)
        dkv_inputs.append(segs)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, cfg=cfg),
        grid=dkv_grid,
        in_specs=dkv_in_specs,
        out_specs=[k_spec, k_spec],
        out_shape=[
            _sds((bh_kv, skv, d), k.dtype, vma=_vma(q, k, v, do)),
            _sds((bh_kv, skv, d), v.dtype, vma=_vma(q, k, v, do)),
        ],
        scratch_shapes=[
            pltpu.VMEM((dkv_out_lead, cfg.block_k, d), jnp.float32),
            pltpu.VMEM((dkv_out_lead, cfg.block_k, d), jnp.float32),
        ],
        compiler_params=semantics,
        interpret=cfg.interpret,
    )(*dkv_inputs)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp core over padded (BH, S, D) arrays
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_core(cfg: _Cfg, q, k, v, segs):
    o, _ = _fwd(cfg, q, k, v, segs)
    return o


def _flash_core_fwd(cfg: _Cfg, q, k, v, segs):
    o, lse = _fwd(cfg, q, k, v, segs)
    return o, (q, k, v, segs, o, lse)


def _flash_core_bwd(cfg: _Cfg, res, do):
    q, k, v, segs, o, lse = res
    dq, dk, dv = _bwd_impl(cfg, q, k, v, o, lse, do, segs)
    d_segs = (None if segs is None
              else np.zeros(segs.shape, jax.dtypes.float0))
    return dq, dk, dv, d_segs


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def _pad_seq(x, mult):
    s = x.shape[1]
    pad = (-s) % mult
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    window: Optional[int] = None,
    segment_ids=None,
    block_q: int = 512,
    block_k: int = 512,
    bh_block: int = 1,
    interpret: Optional[bool] = None,
    return_lse: bool = False,
):
    """Flash attention over ``(batch, heads, seq, head_dim)`` tensors.

    Differentiable (custom VJP). Sequence lengths need not be multiples
    of the block sizes — inputs are zero-padded and masked inside the
    kernel. ``return_lse`` additionally returns the per-row
    log-sum-exp (float32, shape ``(batch, heads, seq)``) for softmax
    merging across shards (ring attention); the lse path is
    forward-only.

    Default blocks are 512x512 (clamped to the sequence): the dominant
    cost at small blocks is per-grid-iteration overhead (window-swap
    DMA setup + scalar control, ~1 us/iteration), not the MXU dots — a
    seq-4096 forward at 128x128 runs 32x more inner iterations than at
    512x512 for identical FLOPs (measured on v5e round 3: the s=1024
    d=128 forward diag sat at ~3.7 TFLOP/s under 128x128). VMEM at
    512x512/d=128 is a few MB against the 128 MB budget; shorter
    sequences clamp down automatically.

    ``window`` (requires ``causal``): sliding-window / local attention —
    each query attends to at most its last ``window`` keys (itself
    included). Key/query blocks wholly outside the band are SKIPPED in
    all three kernels, so compute is O(S·window): the Mistral-style
    long-context lever for sequences where even the causal half of
    S² is too much.

    ``segment_ids`` ((batch, seq) int32, requires equal q/kv lengths):
    sequence-packing mask — positions attend only within their own
    packed document. Rides into the kernels as a whole padded row per
    (batch·head) and masks per (q, k) pair; no block skipping (packed
    documents are block-unaligned by nature).

    Grouped-query attention: pass ``k``/``v`` with FEWER heads than
    ``q`` (``heads % kv_heads == 0``) — the kernels read each K/V head
    at index ``q_head // group`` via their BlockSpec index maps (the
    expanded K/V never materialize in HBM), and the dK/dV kernel's
    inner grid enumerates (group member, q block) so each K/V head's
    gradient accumulates over every query head it serves.

    ``bh_block`` (batched-bh restructure, round-5 short-sequence
    lever): each grid cell processes ``bh_block`` (batch·head) rows as
    an unrolled loop of sub-dots sharing one mask computation and one
    revolving-window DMA per cell. At short sequence the inner grid is
    tiny (s=1024 at 512-blocks → 2×2 per bh row) and per-grid-cell
    overhead dominates the MXU work — the r03 diagnostic's 3.66 TF/s
    at s=1024 vs 46.7 TF/s at 64k with identical block shapes
    (MFU_ANALYSIS §7 / ROUND4_NOTES §2 decision tree). Batching bh
    cuts the cell count ``bh_block``× at identical FLOPs. Clamped to
    the largest value ≤ the request dividing batch·heads exactly —
    and, under grouped-query attention, additionally a multiple of the
    group (the cell's K/V block then carries ``G/group`` rows and the
    dK/dV kernel sweeps the group in-kernel). ``1`` is exactly the
    classic kernel.
    """
    if q.ndim != 4:
        raise ValueError(f"expected (batch, heads, seq, head_dim), got {q.shape}")
    b, h, sq, d = q.shape
    h_kv = k.shape[1]
    skv = k.shape[2]
    if h_kv != h:
        # grouped-query attention: q-head i reads K/V head i // group
        # via the kernels' index maps — K/V are never expanded
        if h_kv < 1 or h % h_kv or v.shape[1] != h_kv:
            raise ValueError(
                f"k/v heads ({h_kv}/{v.shape[1]}) must be equal and "
                f"divide q heads ({h}) for grouped-query attention"
            )
    if causal and sq != skv:
        raise ValueError("causal=True requires equal q/kv sequence lengths")
    if window is not None:
        if not causal:
            raise ValueError("window (sliding-window attention) requires "
                             "causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if segment_ids is not None:
        if sq != skv:
            raise ValueError("segment_ids (sequence packing) requires "
                             "equal q/kv sequence lengths")
        if segment_ids.shape != (b, sq):
            raise ValueError(
                f"segment_ids must be (batch, seq)={(b, sq)}, got "
                f"{segment_ids.shape}"
            )
    if interpret is None:
        from tpuflow.core.hw import is_tpu_backend

        interpret = not is_tpu_backend()
    scale = _static_scale(scale, d)
    block_q = min(block_q, max(8, sq))
    block_k = min(block_k, max(8, skv))
    if bh_block < 1:
        raise ValueError(f"bh_block must be >= 1, got {bh_block}")
    group = h // h_kv
    # VMEM-aware cap first: every input/output block and all three
    # f32 scratch buffers scale with G — an unbounded G=64 at
    # 512-blocks/d=128 is a ~115 MB cell that Mosaic cannot place.
    # Estimate per-row bytes (q+k+v+o double-buffered at the input
    # itemsize, plus the largest kernel's scratch) against a 64 MB
    # budget (half of v5e-class VMEM, headroom for Pallas overhead).
    itemsize = jnp.dtype(q.dtype).itemsize
    per_row = (
        2 * (2 * block_q * d + 2 * block_k * d) * itemsize
        + (2 * block_q * _LANES + block_q * d) * 4  # fwd m/l/acc
        + 2 * block_k * d * 4  # dkv dk/dv accumulators
    )
    vmem_cap = max(1, (64 << 20) // per_row)
    # then the largest G ≤ the request with exact grid cover: G must
    # divide batch·heads, and under GQA additionally be a MULTIPLE of
    # the group (the cell's K/V block carries G/group rows; a
    # non-multiple would make that block zero rows). G=1 is always
    # legal — the classic per-row b//group path.
    bh_block = min(int(bh_block), b * h, vmem_cap)
    while bh_block > 1 and ((b * h) % bh_block or bh_block % group):
        bh_block -= 1
    bh_block = max(1, bh_block)
    cfg = _Cfg(
        causal=causal,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        sq_valid=sq,
        skv_valid=skv,
        interpret=bool(interpret),
        window=None if window is None else int(window),
        has_segments=segment_ids is not None,
        kv_group=h // h_kv,
        bh_block=bh_block,
    )
    qp = _pad_seq(q.reshape(b * h, sq, d), block_q)
    kp = _pad_seq(k.reshape(b * h_kv, skv, d), block_k)
    vp = _pad_seq(v.reshape(b * h_kv, skv, d), block_k)
    segs = None
    if segment_ids is not None:
        # one padded row per (batch·head), fill -1 so padding can never
        # alias a real segment; length covers BOTH padded extents
        pad_len = max(qp.shape[1], kp.shape[1])
        srow = jnp.pad(
            segment_ids.astype(jnp.int32), ((0, 0), (0, pad_len - sq)),
            constant_values=-1,
        )
        segs = jnp.broadcast_to(
            srow[:, None, :], (b, h, pad_len)
        ).reshape(b * h, 1, pad_len)
    if return_lse:
        o, lse = _fwd(cfg, qp, kp, vp, segs)
        return (
            o[:, :sq].reshape(b, h, sq, d),
            lse[:, :sq].reshape(b, h, sq),
        )
    o = _flash_core(cfg, qp, kp, vp, segs)
    return o[:, :sq].reshape(b, h, sq, d)


# ---------------------------------------------------------------------------
# paged-attention decode kernel (ISSUE 11)
#
# The serve engine's paged KV cache (tpuflow.serve.pages) stores KV in
# a process-wide pool of fixed-size pages; each decode row maps its
# logical positions onto physical pages through a per-row page table.
# The portable path in tpuflow.models.transformer scatters the new
# token's K/V into the pool, gathers the row's pages back into a dense
# (B, KVH, L, D) view, and runs plain einsum attention — an O(L)
# materialization per step that a fused kernel makes unnecessary.
#
# ``paged_flash_decode`` is that kernel (vLLM's PagedAttention idea on
# the repo's own online-softmax flash machinery above): ONE fused call
# per decode step that (a) lands the new token's K/V in its page slot
# and (b) runs the blockwise online-softmax read THROUGH the page
# table — the K/V blocks are fetched page-by-page via a scalar-
# prefetched page table driving the BlockSpec index maps, so the
# gather IS the grid walk and nothing dense ever materializes. Page
# blocks above the row's live length are skipped, and the page stores
# ride input_output_aliasing so the token write is in place (composes
# with the serve executables' buffer donation — no O(store) copy).
# ---------------------------------------------------------------------------


class _PagedCfg(NamedTuple):
    """Static config of the paged decode kernel (hashable)."""

    scale: float
    page_size: int
    kv_group: int  # query heads per K/V head (GQA); 1 = MHA
    window: Optional[int]
    interpret: bool


def _paged_decode_ref(q, k_new, v_new, key_pages, value_pages,
                      page_table, pos, write_mask, scale,
                      window: Optional[int] = None):
    """jnp oracle with the kernel's exact contract (tests): scatter the
    new token, gather the dense view, masked softmax — the same math
    the portable einsum path in CausalAttention runs."""
    b = q.shape[0]
    h = q.shape[1]
    kvh = k_new.shape[1]
    g = h // kvh
    ps = key_pages.shape[2]
    d = q.shape[-1]
    n_row = page_table.shape[1]
    pg = jnp.take_along_axis(
        page_table, jnp.clip(pos[:, None] // ps, 0, n_row - 1), axis=1
    )[:, 0]
    pg = jnp.where(write_mask, pg, 0)
    off = pos % ps
    key_pages = key_pages.at[pg, :, off, :].set(k_new)
    value_pages = value_pages.at[pg, :, off, :].set(v_new)
    kf = key_pages[page_table].transpose(0, 2, 1, 3, 4).reshape(
        b, kvh, n_row * ps, d).astype(jnp.float32)
    vf = value_pages[page_table].transpose(0, 2, 1, 3, 4).reshape(
        b, kvh, n_row * ps, d).astype(jnp.float32)
    key_pos = jnp.arange(n_row * ps)
    ok = key_pos[None, :] <= pos[:, None]
    if window is not None:
        ok = ok & (key_pos[None, :] > pos[:, None] - window)
    qg = q.reshape(b, kvh, g, d).astype(jnp.float32)
    s = jnp.einsum("bkgd,bksd->bkgs", qg, kf) * scale
    s = jnp.where(ok[:, None, None], s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgs,bksd->bkgd", p, vf).reshape(b, h, d)
    return o.astype(q.dtype), key_pages, value_pages


def _paged_decode_kernel(table_ref, pos_ref, wm_ref, q_ref, kn_ref,
                         vn_ref, kp_ref, vp_ref, o_ref, kout_ref,
                         vout_ref, m_ref, l_ref, acc_ref, *,
                         cfg: _PagedCfg):
    b = pl.program_id(0)
    j = pl.program_id(1)  # inner: this row's page blocks, sequential
    ps = cfg.page_size
    g = cfg.kv_group
    kvh = kp_ref.shape[1]
    t = pos_ref[b]  # the row's query == write position (clipped by caller)
    last_j = lax.div(t, ps)  # last page block holding visible keys
    first_j = (
        jnp.maximum(lax.div(t - cfg.window + 1, ps), 0)
        if cfg.window is not None else 0
    )

    # pass the page block through (aliased write-back: untouched pages
    # must round-trip bit-identical) ...
    kout_ref[...] = kp_ref[...]
    vout_ref[...] = vp_ref[...]
    # ... and the block owning position t additionally lands the new
    # token's K/V at its slot BEFORE the read below — the fused write.
    # Skipped entirely for masked rows (done / past budget): the
    # portable path scribbles the sink page instead; nobody reads
    # either, and not-writing keeps shared page content bit-stable.
    @pl.when((j == last_j) & (wm_ref[b] != 0))
    def _write():
        off = t - last_j * ps
        sel = lax.broadcasted_iota(jnp.int32, (1, 1, ps, 1), 2) == off
        kout_ref[...] = jnp.where(sel, kn_ref[...][:, :, None, :],
                                  kout_ref[...])
        vout_ref[...] = jnp.where(sel, vn_ref[...][:, :, None, :],
                                  vout_ref[...])

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_BIG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # online softmax over the row's LIVE page blocks only — blocks
    # above the live length (incl. incremental-allocation tail slots
    # still pointing at the sink) are skipped, so per-step work scales
    # with the row's tokens, never with its table width
    @pl.when((j >= first_j) & (j <= last_j))
    def _compute():
        col = j * ps + lax.broadcasted_iota(jnp.int32, (1, ps), 1)[0]
        band = col <= t
        if cfg.window is not None:
            band = band & (col > t - cfg.window)
        for gk in range(kvh):
            # decode is memory-bound (matvec-shaped): everything runs
            # f32 like the portable einsum path it must agree with
            kb = kout_ref[0, gk].astype(jnp.float32)  # (ps, D)
            vb = vout_ref[0, gk].astype(jnp.float32)
            qg = q_ref[0, gk * g:(gk + 1) * g].astype(jnp.float32)
            s = jnp.dot(qg, kb.T,
                        preferred_element_type=jnp.float32) * cfg.scale
            s = jnp.where(band[None, :], s, _NEG_BIG)
            m = m_ref[gk * g:(gk + 1) * g, :1]
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.where(band[None, :], jnp.exp(s - m_new), 0.0)
            alpha = jnp.exp(m - m_new)
            l_new = alpha * l_ref[gk * g:(gk + 1) * g, :1] + jnp.sum(
                p, axis=-1, keepdims=True)
            acc_ref[gk * g:(gk + 1) * g] = (
                acc_ref[gk * g:(gk + 1) * g] * alpha
                + jnp.dot(p, vb, preferred_element_type=jnp.float32))
            m_ref[gk * g:(gk + 1) * g] = jnp.broadcast_to(
                m_new, (g, _LANES))
            l_ref[gk * g:(gk + 1) * g] = jnp.broadcast_to(
                l_new, (g, _LANES))

    @pl.when(j == last_j)
    def _finalize():
        l = l_ref[:, :1]
        safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = jnp.where(l > 0, acc_ref[...] / safe, 0.0).astype(
            o_ref.dtype)


def paged_flash_decode(q, k_new, v_new, key_pages, value_pages,
                       page_table, pos, write_mask=None, *,
                       scale: Optional[float] = None,
                       window: Optional[int] = None,
                       interpret: Optional[bool] = None):
    """Fused paged-attention decode step (one query token per row).

    ``q`` (B, H, D); ``k_new``/``v_new`` (B, KVH, D) — the new token's
    post-rotary K/V; ``key_pages``/``value_pages`` (pages, KVH,
    page_size, D) — the process-wide page pools; ``page_table``
    (B, n_row_pages) int32; ``pos`` (B,) int32 — each row's logical
    write == query position; ``write_mask`` (B,) bool — False rows
    skip the KV write (done rows, rows past their budget).

    Returns ``(o, key_pages, value_pages)`` with ``o`` (B, H, D) and
    the page stores carrying the new token — aliased to the inputs
    (``input_output_aliases``), so under the serve executables' buffer
    donation the write is genuinely in place: per-step cost scales
    with each row's LIVE length (page blocks above it are skipped),
    never with the store size.

    Grouped-query attention is native (``H % KVH == 0``; q-head i
    reads K/V head ``i // group``); ``window`` applies the sliding-
    window mask AND skips page blocks wholly below it. Like every
    kernel in this module it runs in Pallas interpret mode off-TPU,
    where tests pin it against the portable scatter+gather+einsum
    decode path (:func:`_paged_decode_ref` is that oracle).

    Correctness invariant inherited from the page allocator: a page
    WRITTEN this step (a row's exclusive tail page) is mapped by
    exactly one row's table; pages shared between rows (prefix-cache
    chains) are read-only, so every cell's unconditional block
    write-back round-trips them bit-identical. int8-quantized stores
    take the portable path (per-page scale dequant is not fused here).
    """
    if q.ndim != 3:
        raise ValueError(f"expected q (batch, heads, head_dim), got "
                         f"{q.shape}")
    b, h, d = q.shape
    kvh = k_new.shape[1]
    if h % kvh or v_new.shape[1] != kvh:
        raise ValueError(
            f"k/v heads ({kvh}/{v_new.shape[1]}) must be equal and "
            f"divide q heads ({h})")
    npages, kvh_p, ps, d_p = key_pages.shape
    if (kvh_p, d_p) != (kvh, d) or value_pages.shape != key_pages.shape:
        raise ValueError(
            f"page stores {key_pages.shape}/{value_pages.shape} do not "
            f"match (pages, {kvh}, page_size, {d})")
    n_row = page_table.shape[1]
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    if interpret is None:
        from tpuflow.core.hw import is_tpu_backend

        interpret = not is_tpu_backend()
    cfg = _PagedCfg(
        scale=_static_scale(scale, d), page_size=ps, kv_group=h // kvh,
        window=None if window is None else int(window),
        interpret=bool(interpret),
    )
    # clip so last_j stays inside the table even for rows stepped past
    # their budget (their write is masked; their output is discarded)
    posc = jnp.clip(jnp.asarray(pos, jnp.int32), 0, n_row * ps - 1)
    wm = (jnp.ones((b,), jnp.int32) if write_mask is None
          else jnp.asarray(write_mask).astype(jnp.int32))
    kv_spec = pl.BlockSpec((1, kvh, ps, d),
                           lambda b, j, t, p, w: (t[b, j], 0, 0, 0))
    row_spec = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda b, j, t, p, w: (b,) + (0,) * (len(shape) - 1))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, n_row),
        in_specs=[
            row_spec((1, h, d)),    # q
            row_spec((1, kvh, d)),  # k_new
            row_spec((1, kvh, d)),  # v_new
            kv_spec,                # key_pages (via page table)
            kv_spec,                # value_pages
        ],
        out_specs=[row_spec((1, h, d)), kv_spec, kv_spec],
        scratch_shapes=[
            pltpu.VMEM((h, _LANES), jnp.float32),  # running max
            pltpu.VMEM((h, _LANES), jnp.float32),  # normalizer
            pltpu.VMEM((h, d), jnp.float32),       # output accumulator
        ],
    )
    # both grid dims 'arbitrary' (sequential): rows sharing prefix
    # pages write those blocks back concurrently under a parallel b —
    # identical bytes, but nothing here needs to rely on that
    o, kp2, vp2 = pl.pallas_call(
        functools.partial(_paged_decode_kernel, cfg=cfg),
        grid_spec=grid_spec,
        out_shape=[
            _sds((b, h, d), q.dtype),
            _sds(key_pages.shape, key_pages.dtype),
            _sds(value_pages.shape, value_pages.dtype),
        ],
        # operand indices INCLUDE the scalar-prefetch args: the stores
        # are operands 6/7 of (table, pos, wm, q, k_new, v_new, kp, vp)
        input_output_aliases={6: 1, 7: 2},
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=cfg.interpret,
    )(jnp.asarray(page_table, jnp.int32), posc, wm, q, k_new, v_new,
      key_pages, value_pages)
    return o, kp2, vp2
