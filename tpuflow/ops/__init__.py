"""Pallas TPU kernels for the hot ops (SURVEY.md §7 step 4 analogue).

The reference delegates all kernels to TF's C++/CUDA runtime (N4,
P1/02_model_training_single_node.py:123-124,210-215); here the compute
path is XLA, and these Pallas kernels cover the ops XLA's defaults
leave on the table — blockwise flash attention (the hot op of the
attention/long-context model family) with an online-softmax forward and
a recomputation backward.
"""

from tpuflow.ops.attention import (  # noqa: F401
    flash_attention,
    mha_reference,
    mha_xla,
    pick_attn_impl,
)
from tpuflow.ops.xent import fused_linear_token_loss  # noqa: F401
