"""fmin — the hyperopt.fmin equivalent (C14-C15).

≙ ``fmin(fn, space, algo=tpe.suggest, trials, max_evals)``
(P2/01_hyperopt_single_machine_model.py:232-238, P2/02:360-365).
The objective returns ``{'loss': ..., 'status': STATUS_OK}`` — to
maximize accuracy, return ``-accuracy`` as the loss exactly as the
reference does (P2/01:179-181).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from tpuflow.tune.space import Space, sample_space
from tpuflow.tune.tpe import TPE
from tpuflow.tune.trials import STATUS_OK, Trials  # noqa: F401 (re-export)


def fmin(
    fn: Callable[[Dict[str, Any]], Any],
    space: Space,
    max_evals: int = 20,
    algo: str = "tpe",
    trials: Optional[Trials] = None,
    seed: int = 0,
    verbose: bool = False,
    pruner=None,
) -> Dict[str, Any]:
    """Minimize ``fn`` over ``space``; returns the best params dict.

    ``trials``: Trials (sequential; required when fn is itself
    distributed, ≙ P2/02:341-344) or ParallelTrials (concurrent
    single-device trials, ≙ SparkTrials). Inspect ``trials.results``
    afterwards for the full record.

    ``pruner``: e.g. ``tune.pruning.MedianPruner()`` — early-stops
    unpromising trials whose objective reports intermediate values via
    a ``report(step, value)`` keyword (see tpuflow/tune/pruning.py;
    beyond the reference, whose Hyperopt always runs trials to the
    end).
    """
    trials = trials if trials is not None else Trials()
    import numpy as np

    tpe = TPE(seed=seed)
    rng = np.random.default_rng(seed + 1)
    tid = len(trials.results)
    while tid < max_evals:
        batch_size = min(trials.suggest_batch_size(), max_evals - tid)
        history = [(t.params, t.loss) for t in trials.results]
        batch = []
        for _ in range(batch_size):
            if algo == "random":
                params = sample_space(space, rng)
            else:
                params = tpe.suggest(space, history)
            # pending in-batch params carry inf loss: excluded from the
            # Parzen model; sampling stochasticity diversifies the batch
            history = history + [(params, float("inf"))]
            batch.append(params)
        new = trials.run_batch(fn, batch, tid, pruner=pruner)
        tid += len(new)
        if verbose:
            from tpuflow.tune.trials import STATUS_PRUNED

            for t in new:
                msg = f"trial {t.tid}: loss={t.loss:.5f} params={t.params}"
                if t.status == STATUS_PRUNED:
                    msg += f" pruned at step {t.extra.get('pruned_at', '?')}"
                elif t.status != STATUS_OK:
                    msg += f" FAILED: {t.extra.get('error', 'unknown')}"
                print(msg)
    return trials.best().params
