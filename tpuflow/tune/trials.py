"""Trial bookkeeping + the trial execution topologies (C14-C15, N9).

The reference's split, preserved deliberately (SURVEY.md §2 C14-C15):

- ``ParallelTrials`` ≙ ``SparkTrials(parallelism=k)``
  (P2/01_hyperopt_single_machine_model.py:229): k single-device
  objectives run CONCURRENTLY, each pinned to a disjoint device subset
  of the local mesh (the TPU analogue of one-trial-per-executor).
  Thread-based — light, shares the parent's JAX runtime; concurrent
  trials contend the GIL and jit cache during tracing/compilation.
- ``ProcessTrials``: the same semantics with one OS PROCESS per
  in-flight trial (the honest SparkTrials analogue — Spark executors
  are processes): each child owns its own Python interpreter, JAX
  runtime and compilation cache, so k compile-heavy trials scale with
  cores instead of serializing on the GIL (VERDICT r2 #6). Objectives
  must be picklable (module-level functions); the pruner protocol is
  forwarded over a per-trial pipe.
- ``Trials`` ≙ hyperopt's default driver-side Trials — REQUIRED for
  objectives that are themselves distributed over the whole pod, which
  must launch sequentially from the driver (the documented constraint
  at P2/02:341-344).
"""

from __future__ import annotations

import threading
import traceback
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

STATUS_OK = "ok"
STATUS_FAIL = "fail"
STATUS_PRUNED = "pruned"  # stopped early by a pruner (tune.pruning)


@dataclass
class TrialResult:
    tid: int
    params: Dict[str, Any]
    loss: float
    status: str
    extra: Dict[str, Any] = field(default_factory=dict)


class Trials:
    """Sequential, driver-side trial execution + record of results."""

    def __init__(self):
        self.results: List[TrialResult] = []
        self._lock = threading.Lock()

    @property
    def losses(self) -> List[float]:
        return [t.loss for t in self.results]

    def best(self) -> TrialResult:
        ok = [t for t in self.results if t.status == STATUS_OK]
        if not ok:
            raise ValueError("no successful trials")
        return min(ok, key=lambda t: t.loss)

    def record(self, tid, params, outcome) -> TrialResult:
        loss, status, extra = _normalize(outcome)
        tr = TrialResult(tid, params, loss, status, extra)
        with self._lock:
            self.results.append(tr)
        return tr

    # -- execution --------------------------------------------------------

    def run_batch(
        self, fn: Callable, batch: List[Dict[str, Any]], start_tid: int,
        pruner=None,
    ) -> List[TrialResult]:
        takes_report = _takes_report(fn)
        out = []
        for i, params in enumerate(batch):
            tid = start_tid + i
            kw = _report_kw(takes_report, pruner, tid)
            tr = self.record(tid, params, _safe_call(fn, params, **kw))
            _settle_pruner(pruner, tid, tr.status)
            out.append(tr)
        return out

    def suggest_batch_size(self) -> int:
        return 1


class ParallelTrials(Trials):
    """Concurrent trials over disjoint device subsets.

    Each in-flight trial gets ``devices`` (a list of jax.Device) if the
    objective accepts that keyword — the mesh-scoping hook that turns
    one pod into k independent trial slots (SURVEY.md §7 hard part 4).
    """

    def __init__(self, parallelism: int = 4, devices: Optional[List] = None):
        super().__init__()
        import jax

        self.parallelism = max(1, parallelism)
        devs = list(devices if devices is not None else jax.devices())
        k = min(self.parallelism, len(devs))
        per = len(devs) // k
        self.device_groups = [devs[i * per : (i + 1) * per] for i in range(k)]

    def suggest_batch_size(self) -> int:
        return self.parallelism

    def run_batch(self, fn, batch, start_tid, pruner=None) -> List[TrialResult]:
        import inspect

        takes_devices = "devices" in inspect.signature(fn).parameters
        takes_report = _takes_report(fn)
        results: List[Optional[TrialResult]] = [None] * len(batch)

        def one(i: int, params):
            tid = start_tid + i
            kw = _report_kw(takes_report, pruner, tid)
            if takes_devices:
                kw["devices"] = self.device_groups[i % len(self.device_groups)]
            outcome = _safe_call(fn, params, **kw)
            results[i] = self.record(tid, params, outcome)
            _settle_pruner(pruner, tid, results[i].status)

        with ThreadPoolExecutor(max_workers=self.parallelism) as ex:
            futs = [ex.submit(one, i, p) for i, p in enumerate(batch)]
            for f in futs:
                f.result()
        return [r for r in results if r is not None]


def _child_main(conn, fn_bytes: bytes, params: Dict[str, Any],
                device_ids: Optional[List[int]], env: Dict[str, str],
                takes_devices: bool, takes_report: bool,
                has_pruner: bool) -> None:
    """Trial subprocess entry (module-level for spawn picklability).

    Order matters: env overrides are applied BEFORE the objective is
    unpickled, so a child can retarget its JAX platform / visible
    devices (e.g. ``JAX_PLATFORMS=cpu`` +
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N``) before
    anything imports jax. The ``report`` pruner hook round-trips over
    the pipe: child sends (step, value), parent answers
    ``"prune"``/``"ok"`` after consulting the shared pruner."""
    import os
    import pickle

    os.environ.update(env)
    try:
        fn = pickle.loads(fn_bytes)
        kw: Dict[str, Any] = {}
        if takes_devices:
            import jax

            devs = jax.devices()
            kw["devices"] = (
                [devs[i] for i in device_ids] if device_ids else devs
            )
        if takes_report:

            def report(step, value):
                if not has_pruner:
                    return
                conn.send(("report", int(step), float(value)))
                reply = conn.recv()
                if reply == "prune":
                    from tpuflow.tune.pruning import Pruned

                    raise Pruned(step=int(step), best_value=float(value))
                if isinstance(reply, tuple) and reply[0] == "fail":
                    # the parent-side pruner itself blew up — a FAILED
                    # trial, not a pruned one
                    raise RuntimeError(f"pruner error: {reply[1]}")

            kw["report"] = report if has_pruner else None
        outcome = _safe_call(fn, params, **kw)
        conn.send(("done", outcome))
    except BaseException as e:  # never die silently — report and exit 0
        conn.send(("done", {
            "loss": float("inf"),
            "status": STATUS_FAIL,
            "error": f"{type(e).__name__}: {e}",
            "traceback": traceback.format_exc(),
        }))
    finally:
        conn.close()


class ProcessTrials(Trials):
    """Concurrent trials, one OS process per in-flight trial.

    The process-isolated peer of :class:`ParallelTrials` (which shares
    one interpreter across trial threads): each trial child owns its
    own GIL, JAX runtime and jit cache, so tracing/compiling k trials
    concurrently actually uses k cores — the honest analogue of
    SparkTrials' executor processes (P2/01:229).

    ``child_env``: env-var overrides applied in each child BEFORE jax
    imports — either a dict (same for all trials) or a callable
    ``(slot, device_ids) -> dict`` for per-slot targeting (e.g.
    ``TPU_VISIBLE_CHIPS``). ``n_devices`` splits device INDICES
    ``0..n_devices-1`` into ``parallelism`` disjoint groups, resolved
    to real ``jax.Device`` objects inside each child (device handles
    do not cross process boundaries).

    Objectives must be module-level (picklable) functions, and the
    LAUNCHING script must be import-safe (guard top-level work with
    ``if __name__ == "__main__":``) — the standard multiprocessing
    spawn requirement: each child re-imports the parent's main module
    to unpickle the objective. Failures and prunes are isolated per
    child, same contract as the thread mode.
    """

    def __init__(
        self,
        parallelism: int = 4,
        n_devices: Optional[int] = None,
        child_env: Union[Dict[str, str], Callable, None] = None,
    ):
        super().__init__()
        self.parallelism = max(1, parallelism)
        self.n_devices = n_devices
        self.child_env = child_env
        if n_devices is not None and n_devices >= self.parallelism:
            per = n_devices // self.parallelism
            self.device_groups: List[Optional[List[int]]] = [
                list(range(i * per, (i + 1) * per))
                for i in range(self.parallelism)
            ]
        else:
            # unknown/undersubscribed topology: children see all their
            # visible devices (child_env is the targeting hook then)
            self.device_groups = [None] * self.parallelism

    def suggest_batch_size(self) -> int:
        return self.parallelism

    def _env_for(self, slot: int) -> Dict[str, str]:
        if self.child_env is None:
            return {}
        if callable(self.child_env):
            return dict(self.child_env(slot, self.device_groups[slot]))
        return dict(self.child_env)

    def run_batch(self, fn, batch, start_tid, pruner=None) -> List[TrialResult]:
        import inspect
        import multiprocessing as mp
        import pickle

        try:
            fn_bytes = pickle.dumps(fn)
        except Exception as e:
            raise ValueError(
                "ProcessTrials requires a picklable objective (a "
                "module-level function); for closures use the "
                f"thread-based ParallelTrials. Pickle error: {e}"
            ) from None
        sig = inspect.signature(fn).parameters
        takes_devices = "devices" in sig
        takes_report = _takes_report(fn)
        ctx = mp.get_context("spawn")  # never fork a jax-initialized parent
        results: List[Optional[TrialResult]] = [None] * len(batch)
        # slots hand out device groups: a FREE-SLOT QUEUE, not i %
        # parallelism — with len(batch) > parallelism and uneven trial
        # durations the modulo scheme could run two live children on
        # the same device group / child_env target
        import queue as _queue

        free_slots: "_queue.Queue[int]" = _queue.Queue()
        for s in range(self.parallelism):
            free_slots.put(s)

        def one(i: int, params):
            tid = start_tid + i
            slot = free_slots.get()
            try:
                outcome = self._run_child(
                    ctx, tid, params, slot, fn_bytes,
                    takes_devices, takes_report, pruner,
                )
            finally:
                free_slots.put(slot)
            results[i] = self.record(tid, params, outcome)
            _settle_pruner(pruner, tid, results[i].status)

        # service all children concurrently from parent threads (each
        # blocks on its own pipe; the heavy work is in the subprocesses)
        with ThreadPoolExecutor(max_workers=self.parallelism) as ex:
            futs = [ex.submit(one, i, p) for i, p in enumerate(batch)]
            for f in futs:
                f.result()
        return [r for r in results if r is not None]

    def _run_child(self, ctx, tid, params, slot, fn_bytes,
                   takes_devices, takes_report, pruner):
        """Spawn one trial child on ``slot``'s device group and service
        its pipe until it reports done (or dies). Returns the outcome."""
        from tpuflow.tune.pruning import Pruned

        parent_conn, child_conn = ctx.Pipe()
        proc = ctx.Process(
            target=_child_main,
            args=(child_conn, fn_bytes, params,
                  self.device_groups[slot], self._env_for(slot),
                  takes_devices, takes_report, pruner is not None),
            daemon=True,
        )
        proc.start()
        child_conn.close()
        outcome: Any = {
            "loss": float("inf"), "status": STATUS_FAIL,
            "error": "trial process died without reporting",
        }
        try:
            while True:
                msg = parent_conn.recv()
                if msg[0] == "done":
                    outcome = msg[1]
                    break
                _, step, value = msg  # "report"
                try:
                    pruner.report(tid, step, value)
                    parent_conn.send("ok")
                except Pruned:  # → tell the child to stop cleanly
                    parent_conn.send("prune")
                except Exception as e:
                    # pruner BUG → failed trial, not a silent mass-prune
                    parent_conn.send(("fail", f"{type(e).__name__}: {e}"))
        except EOFError:
            pass  # child died: keep the default failure outcome
        finally:
            proc.join()
            parent_conn.close()
        return outcome


def _takes_report(fn) -> bool:
    import inspect

    return "report" in inspect.signature(fn).parameters


def _report_kw(takes_report: bool, pruner, tid) -> Dict[str, Any]:
    """The ``report`` hook, bound to this trial — only when the
    objective declares the keyword (same convention as ``devices``)."""
    if not takes_report:
        return {}
    if pruner is None:
        return {"report": None}
    return {"report": lambda step, value: pruner.report(tid, step, value)}


def _settle_pruner(pruner, tid: int, status: str) -> None:
    """Completion protocol: finished trials join the pruner's median
    set; pruned/failed trials are forgotten (no id collisions later)."""
    if pruner is None:
        return
    if status == STATUS_OK:
        pruner.finish(tid)
    else:
        pruner.discard(tid)


def _safe_call(fn, params, **kw):
    from tpuflow.tune.pruning import Pruned

    try:
        return fn(params, **kw)
    except Pruned as p:  # early stop, not a failure: keep the signal
        return {
            "loss": p.best_value,
            "status": STATUS_PRUNED,
            "pruned_at": p.step,
        }
    except Exception as e:  # a failed trial must not kill the sweep
        return {
            "loss": float("inf"),
            "status": STATUS_FAIL,
            "error": f"{type(e).__name__}: {e}",
            "traceback": traceback.format_exc(),
        }


def _normalize(outcome) -> tuple:
    if isinstance(outcome, dict):
        loss = float(outcome.get("loss", float("inf")))
        status = outcome.get("status", STATUS_OK)
        extra = {k: v for k, v in outcome.items() if k not in ("loss", "status")}
        return loss, status, extra
    return float(outcome), STATUS_OK, {}
