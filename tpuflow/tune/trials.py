"""Trial bookkeeping + the two execution topologies (C14-C15, N9).

The reference's split, preserved deliberately (SURVEY.md §2 C14-C15):

- ``ParallelTrials`` ≙ ``SparkTrials(parallelism=k)``
  (P2/01_hyperopt_single_machine_model.py:229): k single-device
  objectives run CONCURRENTLY, each pinned to a disjoint device subset
  of the local mesh (the TPU analogue of one-trial-per-executor).
- ``Trials`` ≙ hyperopt's default driver-side Trials — REQUIRED for
  objectives that are themselves distributed over the whole pod, which
  must launch sequentially from the driver (the documented constraint
  at P2/02:341-344).
"""

from __future__ import annotations

import threading
import traceback
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

STATUS_OK = "ok"
STATUS_FAIL = "fail"
STATUS_PRUNED = "pruned"  # stopped early by a pruner (tune.pruning)


@dataclass
class TrialResult:
    tid: int
    params: Dict[str, Any]
    loss: float
    status: str
    extra: Dict[str, Any] = field(default_factory=dict)


class Trials:
    """Sequential, driver-side trial execution + record of results."""

    def __init__(self):
        self.results: List[TrialResult] = []
        self._lock = threading.Lock()

    @property
    def losses(self) -> List[float]:
        return [t.loss for t in self.results]

    def best(self) -> TrialResult:
        ok = [t for t in self.results if t.status == STATUS_OK]
        if not ok:
            raise ValueError("no successful trials")
        return min(ok, key=lambda t: t.loss)

    def record(self, tid, params, outcome) -> TrialResult:
        loss, status, extra = _normalize(outcome)
        tr = TrialResult(tid, params, loss, status, extra)
        with self._lock:
            self.results.append(tr)
        return tr

    # -- execution --------------------------------------------------------

    def run_batch(
        self, fn: Callable, batch: List[Dict[str, Any]], start_tid: int,
        pruner=None,
    ) -> List[TrialResult]:
        takes_report = _takes_report(fn)
        out = []
        for i, params in enumerate(batch):
            tid = start_tid + i
            kw = _report_kw(takes_report, pruner, tid)
            tr = self.record(tid, params, _safe_call(fn, params, **kw))
            _settle_pruner(pruner, tid, tr.status)
            out.append(tr)
        return out

    def suggest_batch_size(self) -> int:
        return 1


class ParallelTrials(Trials):
    """Concurrent trials over disjoint device subsets.

    Each in-flight trial gets ``devices`` (a list of jax.Device) if the
    objective accepts that keyword — the mesh-scoping hook that turns
    one pod into k independent trial slots (SURVEY.md §7 hard part 4).
    """

    def __init__(self, parallelism: int = 4, devices: Optional[List] = None):
        super().__init__()
        import jax

        self.parallelism = max(1, parallelism)
        devs = list(devices if devices is not None else jax.devices())
        k = min(self.parallelism, len(devs))
        per = len(devs) // k
        self.device_groups = [devs[i * per : (i + 1) * per] for i in range(k)]

    def suggest_batch_size(self) -> int:
        return self.parallelism

    def run_batch(self, fn, batch, start_tid, pruner=None) -> List[TrialResult]:
        import inspect

        takes_devices = "devices" in inspect.signature(fn).parameters
        takes_report = _takes_report(fn)
        results: List[Optional[TrialResult]] = [None] * len(batch)

        def one(i: int, params):
            tid = start_tid + i
            kw = _report_kw(takes_report, pruner, tid)
            if takes_devices:
                kw["devices"] = self.device_groups[i % len(self.device_groups)]
            outcome = _safe_call(fn, params, **kw)
            results[i] = self.record(tid, params, outcome)
            _settle_pruner(pruner, tid, results[i].status)

        with ThreadPoolExecutor(max_workers=self.parallelism) as ex:
            futs = [ex.submit(one, i, p) for i, p in enumerate(batch)]
            for f in futs:
                f.result()
        return [r for r in results if r is not None]


def _takes_report(fn) -> bool:
    import inspect

    return "report" in inspect.signature(fn).parameters


def _report_kw(takes_report: bool, pruner, tid) -> Dict[str, Any]:
    """The ``report`` hook, bound to this trial — only when the
    objective declares the keyword (same convention as ``devices``)."""
    if not takes_report:
        return {}
    if pruner is None:
        return {"report": None}
    return {"report": lambda step, value: pruner.report(tid, step, value)}


def _settle_pruner(pruner, tid: int, status: str) -> None:
    """Completion protocol: finished trials join the pruner's median
    set; pruned/failed trials are forgotten (no id collisions later)."""
    if pruner is None:
        return
    if status == STATUS_OK:
        pruner.finish(tid)
    else:
        pruner.discard(tid)


def _safe_call(fn, params, **kw):
    from tpuflow.tune.pruning import Pruned

    try:
        return fn(params, **kw)
    except Pruned as p:  # early stop, not a failure: keep the signal
        return {
            "loss": p.best_value,
            "status": STATUS_PRUNED,
            "pruned_at": p.step,
        }
    except Exception as e:  # a failed trial must not kill the sweep
        return {
            "loss": float("inf"),
            "status": STATUS_FAIL,
            "error": f"{type(e).__name__}: {e}",
            "traceback": traceback.format_exc(),
        }


def _normalize(outcome) -> tuple:
    if isinstance(outcome, dict):
        loss = float(outcome.get("loss", float("inf")))
        status = outcome.get("status", STATUS_OK)
        extra = {k: v for k, v in outcome.items() if k not in ("loss", "status")}
        return loss, status, extra
    return float(outcome), STATUS_OK, {}
