"""Hyperparameter search space (C14-C15) — the hyperopt.hp equivalent.

≙ the space constructors the reference uses: ``hp.choice`` over
optimizer names / batch sizes, ``hp.loguniform`` for LR,
``hp.uniform`` for dropout (P2/01_hyperopt_single_machine_model.py:194-198,
P2/02_hyperopt_distributed_model.py:322-326). Same semantics:
``loguniform(low, high)`` samples exp(U(low, high)).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, List, Sequence

import numpy as np


@dataclass(frozen=True)
class Dimension:
    kind: str  # choice | uniform | loguniform | quniform | randint
    options: tuple = ()
    low: float = 0.0
    high: float = 1.0
    q: float = 1.0

    # -- sampling ---------------------------------------------------------

    def sample(self, rng: np.random.Generator) -> Any:
        if self.kind == "choice":
            return self.options[int(rng.integers(len(self.options)))]
        if self.kind == "uniform":
            return float(rng.uniform(self.low, self.high))
        if self.kind == "loguniform":
            return float(math.exp(rng.uniform(self.low, self.high)))
        if self.kind == "quniform":
            v = rng.uniform(self.low, self.high)
            return float(round(v / self.q) * self.q)
        if self.kind == "randint":
            return int(rng.integers(self.low, self.high))
        raise ValueError(self.kind)

    # -- mapping to the real line for the Parzen estimators ---------------

    def to_unit(self, value: Any) -> float:
        if self.kind == "choice":
            return float(self.options.index(value))
        if self.kind == "loguniform":
            return math.log(value)
        return float(value)

    def from_unit(self, x: float) -> Any:
        if self.kind == "choice":
            return self.options[int(np.clip(round(x), 0, len(self.options) - 1))]
        if self.kind == "loguniform":
            return float(math.exp(np.clip(x, self.low, self.high)))
        if self.kind == "quniform":
            return float(round(np.clip(x, self.low, self.high) / self.q) * self.q)
        if self.kind == "randint":
            return int(np.clip(round(x), self.low, self.high - 1))
        return float(np.clip(x, self.low, self.high))

    def bounds(self) -> tuple:
        if self.kind == "choice":
            return (0.0, float(len(self.options) - 1))
        return (self.low, self.high)


class hp:
    """Namespace mirroring hyperopt.hp (name arg omitted: the dict key
    names the dimension)."""

    @staticmethod
    def choice(options: Sequence[Any]) -> Dimension:
        return Dimension("choice", options=tuple(options))

    @staticmethod
    def uniform(low: float, high: float) -> Dimension:
        return Dimension("uniform", low=low, high=high)

    @staticmethod
    def loguniform(low: float, high: float) -> Dimension:
        """exp(U(low, high)) — low/high are in LOG space (hyperopt
        convention; the reference uses loguniform(-5, 0) for LR ∈
        [exp(-5), 1], P2/01:196)."""
        return Dimension("loguniform", low=low, high=high)

    @staticmethod
    def quniform(low: float, high: float, q: float) -> Dimension:
        return Dimension("quniform", low=low, high=high, q=q)

    @staticmethod
    def randint(low: int, high: int) -> Dimension:
        return Dimension("randint", low=low, high=high)


Space = Dict[str, Dimension]


def sample_space(space: Space, rng: np.random.Generator) -> Dict[str, Any]:
    return {k: d.sample(rng) for k, d in space.items()}
