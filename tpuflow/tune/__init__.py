from tpuflow.tune.space import hp  # noqa: F401
from tpuflow.tune.fmin import fmin, STATUS_OK  # noqa: F401
from tpuflow.tune.trials import ParallelTrials, Trials  # noqa: F401
