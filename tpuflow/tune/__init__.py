from tpuflow.tune.space import hp  # noqa: F401
from tpuflow.tune.fmin import fmin, STATUS_OK  # noqa: F401
from tpuflow.tune.trials import (  # noqa: F401
    ParallelTrials,
    ProcessTrials,
    STATUS_PRUNED,
    Trials,
)
from tpuflow.tune.pruning import AshaPruner, MedianPruner, Pruned  # noqa: F401
