"""Trial pruning — early termination of unpromising HPO trials.

BEYOND-REFERENCE capability: the reference's Hyperopt runs every trial
to completion (P2/01:232-238 — 20 full trainings); with epoch-level
reporting a sweep on expensive objectives spends most of its budget on
obviously-bad configurations. The median stopping rule (Golovin et al.
2017, "Google Vizier"; the default pruner in Optuna) kills a trial
whose best intermediate value is worse than the median of what
completed trials had achieved by the same step.

Contract: the objective accepts a ``report`` keyword (mirrors the
``devices`` convention of ParallelTrials) and calls
``report(step, value)`` after each epoch; ``report`` raises ``Pruned``
when the trial should stop. ``fmin`` catches it and records the trial
with status 'pruned' and the best value it reached — still useful
signal for the TPE history.

    def objective(params, report=None):
        for epoch in range(EPOCHS):
            val_loss = train_one_epoch(...)
            if report is not None:
                report(epoch, val_loss)
        return {"loss": val_loss, "status": "ok"}
"""

from __future__ import annotations

import threading
from typing import Dict, List


class Pruned(Exception):
    """Raised by a pruner's report() to stop the calling trial; carries
    the best intermediate value observed so far."""

    def __init__(self, step: int, best_value: float):
        super().__init__(f"pruned at step {step} (best {best_value:.6g})")
        self.step = step
        self.best_value = best_value


class MedianPruner:
    """Median stopping rule over per-step intermediate values.

    A trial reporting at ``step`` is pruned when its best value so far
    is strictly worse than the median of the FINISHED trials' best
    values at that same step. ``warmup_steps`` reports are always
    allowed, and nothing is pruned until ``min_trials`` trials have
    finished (the median needs support). Thread-safe — ParallelTrials
    runs trials concurrently in one process.
    """

    def __init__(self, warmup_steps: int = 1, min_trials: int = 3):
        self.warmup_steps = max(0, warmup_steps)
        self.min_trials = max(1, min_trials)
        self._lock = threading.Lock()
        # finished trials: tid -> {step: best_value_up_to_step}
        self._finished: Dict[int, Dict[int, float]] = {}
        self._live: Dict[int, Dict[int, float]] = {}

    def _best_through(self, values: Dict[int, float], step: int) -> float:
        eligible = [v for s, v in values.items() if s <= step]
        return min(eligible) if eligible else float("inf")

    def report(self, tid: int, step: int, value: float) -> None:
        """Record an intermediate value; raise Pruned to stop the trial."""
        value = float(value)
        with self._lock:
            rec = self._live.setdefault(tid, {})
            rec[step] = min(value, rec.get(step, float("inf")))
            if step < self.warmup_steps:
                return
            if len(self._finished) < self.min_trials:
                return
            peers: List[float] = [
                self._best_through(v, step) for v in self._finished.values()
            ]
            peers = [p for p in peers if p != float("inf")]
            if not peers:
                return
            import statistics

            median = statistics.median(peers)
            mine = self._best_through(rec, step)
            if mine > median:
                # drop the live record before raising: a reused pruner
                # (second fmin run, tids restarting at 0) must not merge
                # a new trial's curve into this one's
                self._live.pop(tid, None)
                raise Pruned(step, mine)

    def finish(self, tid: int) -> None:
        """Move a trial's record into the comparison set (call when the
        trial COMPLETES; pruned trials stay out of the median)."""
        with self._lock:
            rec = self._live.pop(tid, None)
            if rec:
                self._finished[tid] = rec

    def discard(self, tid: int) -> None:
        """Forget a trial that ended without completing (failed/pruned
        outside report()) so its record cannot collide with a later
        trial of the same id."""
        with self._lock:
            self._live.pop(tid, None)
