"""Trial pruning — early termination of unpromising HPO trials.

BEYOND-REFERENCE capability: the reference's Hyperopt runs every trial
to completion (P2/01:232-238 — 20 full trainings); with epoch-level
reporting a sweep on expensive objectives spends most of its budget on
obviously-bad configurations. The median stopping rule (Golovin et al.
2017, "Google Vizier"; the default pruner in Optuna) kills a trial
whose best intermediate value is worse than the median of what
completed trials had achieved by the same step.

Contract: the objective accepts a ``report`` keyword (mirrors the
``devices`` convention of ParallelTrials) and calls
``report(step, value)`` after each epoch; ``report`` raises ``Pruned``
when the trial should stop. ``fmin`` catches it and records the trial
with status 'pruned' and the best value it reached — still useful
signal for the TPE history.

    def objective(params, report=None):
        for epoch in range(EPOCHS):
            val_loss = train_one_epoch(...)
            if report is not None:
                report(epoch, val_loss)
        return {"loss": val_loss, "status": "ok"}
"""

from __future__ import annotations

import threading
from typing import Dict, List


class Pruned(Exception):
    """Raised by a pruner's report() to stop the calling trial; carries
    the best intermediate value observed so far."""

    def __init__(self, step: int, best_value: float):
        super().__init__(f"pruned at step {step} (best {best_value:.6g})")
        self.step = step
        self.best_value = best_value


class MedianPruner:
    """Median stopping rule over per-step intermediate values.

    A trial reporting at ``step`` is pruned when its best value so far
    is strictly worse than the median of the FINISHED trials' best
    values at that same step. ``warmup_steps`` reports are always
    allowed, and nothing is pruned until ``min_trials`` trials have
    finished (the median needs support). Thread-safe — ParallelTrials
    runs trials concurrently in one process.
    """

    def __init__(self, warmup_steps: int = 1, min_trials: int = 3):
        self.warmup_steps = max(0, warmup_steps)
        self.min_trials = max(1, min_trials)
        self._lock = threading.Lock()
        # finished trials: tid -> {step: best_value_up_to_step}
        self._finished: Dict[int, Dict[int, float]] = {}
        self._live: Dict[int, Dict[int, float]] = {}

    def _best_through(self, values: Dict[int, float], step: int) -> float:
        eligible = [v for s, v in values.items() if s <= step]
        return min(eligible) if eligible else float("inf")

    def report(self, tid: int, step: int, value: float) -> None:
        """Record an intermediate value; raise Pruned to stop the trial."""
        value = float(value)
        with self._lock:
            rec = self._live.setdefault(tid, {})
            rec[step] = min(value, rec.get(step, float("inf")))
            if step < self.warmup_steps:
                return
            if len(self._finished) < self.min_trials:
                return
            peers: List[float] = [
                self._best_through(v, step) for v in self._finished.values()
            ]
            peers = [p for p in peers if p != float("inf")]
            if not peers:
                return
            import statistics

            median = statistics.median(peers)
            mine = self._best_through(rec, step)
            if mine > median:
                # drop the live record before raising: a reused pruner
                # (second fmin run, tids restarting at 0) must not merge
                # a new trial's curve into this one's
                self._live.pop(tid, None)
                raise Pruned(step, mine)

    def finish(self, tid: int) -> None:
        """Move a trial's record into the comparison set (call when the
        trial COMPLETES; pruned trials stay out of the median)."""
        with self._lock:
            rec = self._live.pop(tid, None)
            if rec:
                self._finished[tid] = rec

    def discard(self, tid: int) -> None:
        """Forget a trial that ended without completing (failed/pruned
        outside report()) so its record cannot collide with a later
        trial of the same id."""
        with self._lock:
            self._live.pop(tid, None)


class AshaPruner:
    """Asynchronous Successive Halving (ASHA — Li et al. 2018), the
    rung-based complement to the median rule: aggressive geometric
    budget allocation for LARGE sweeps.

    Rungs sit at steps ``min_resource * reduction_factor**k``. When a
    trial first reports at (or past) a rung it records its best value
    so far there and continues only if that value places in the top
    ``1/reduction_factor`` of everything recorded at that rung —
    ASYNCHRONOUSLY: the comparison runs against whatever has arrived,
    never waiting for a cohort (the 'A' that makes successive halving
    usable with parallel trials). ``min_peers`` guards the cold start
    (the first trials through a rung pass unjudged). Same contract as
    MedianPruner (``report``/``finish``/``discard``; ``report`` raises
    :class:`Pruned`), so it drops into ``fmin(pruner=...)`` and every
    trial topology unchanged. Thread-safe.
    """

    def __init__(self, min_resource: int = 1, reduction_factor: int = 3,
                 min_peers: int = 3):
        if min_resource < 1:
            raise ValueError(f"min_resource must be >= 1, got {min_resource}")
        if reduction_factor < 2:
            raise ValueError(
                f"reduction_factor must be >= 2, got {reduction_factor}"
            )
        self.min_resource = int(min_resource)
        self.eta = int(reduction_factor)
        self.min_peers = max(1, int(min_peers))
        self._lock = threading.Lock()
        self._rungs: Dict[int, List[float]] = {}  # rung step -> values
        self._best: Dict[int, float] = {}  # live tid -> best so far
        # live tid -> {rung: contributed value}: finish() keeps these
        # in the rung history (they ARE the comparison record — pruned
        # trials' true values included, canonical ASHA), discard()
        # REMOVES them (a failed trial's values may be bogus — one
        # spurious 0.0 from a crashed eval would otherwise prune every
        # healthy successor at that rung forever)
        self._contrib: Dict[int, Dict[int, float]] = {}

    def _rung_steps(self, step: int) -> List[int]:
        out, r = [], self.min_resource
        while r <= step:
            out.append(r)
            r *= self.eta
        return out

    def report(self, tid: int, step: int, value: float) -> None:
        """Record an intermediate value; raise Pruned at a rung the
        trial does not survive."""
        import math

        value = float(value)
        with self._lock:
            if not math.isfinite(value):
                # a NaN/inf intermediate is a DIVERGED trial — the
                # canonical prune target. Never let it into the rung
                # history (NaN makes sorted() orderings arbitrary and
                # would silently disable the rung's cutoff forever)
                best = self._best.get(tid, value)
                self._drop_live(tid)
                raise Pruned(step, best)
            best = min(value, self._best.get(tid, float("inf")))
            self._best[tid] = best
            contrib = self._contrib.setdefault(tid, {})
            for rung in self._rung_steps(step):
                if rung in contrib:
                    continue
                vals = self._rungs.setdefault(rung, [])
                vals.append(best)
                contrib[rung] = best
                if len(vals) < self.min_peers:
                    continue
                keep = max(1, len(vals) // self.eta)
                cutoff = sorted(vals)[keep - 1]
                if best > cutoff:
                    # rung history keeps this value (it IS the record
                    # later arrivals compare against); only the live
                    # per-trial state drops
                    self._drop_live(tid)
                    raise Pruned(step, best)

    def _drop_live(self, tid: int) -> None:
        self._best.pop(tid, None)
        self._contrib.pop(tid, None)

    def finish(self, tid: int) -> None:
        """Trial completed: drop live state (rung records persist —
        they are the comparison history)."""
        with self._lock:
            self._drop_live(tid)

    def discard(self, tid: int) -> None:
        """Trial FAILED (or was pruned outside report): remove its
        rung contributions — bogus values from a crashed objective
        must not become the cutoff every healthy successor is judged
        against."""
        with self._lock:
            for rung, v in self._contrib.pop(tid, {}).items():
                vals = self._rungs.get(rung)
                if vals is not None:
                    try:
                        vals.remove(v)
                    except ValueError:
                        pass
            self._best.pop(tid, None)
