"""Tree-structured Parzen Estimator (N9) — the hyperopt.tpe equivalent.

The algorithm of Bergstra et al. 2011 ("Algorithms for Hyper-Parameter
Optimization"), implemented natively: split observed trials into good
(best gamma-quantile by loss) and bad; model each dimension with Parzen
windows (Gaussian kernels for numeric dims, smoothed categorical counts
for choices); sample candidates from the good model and keep the one
maximizing l(x)/g(x) (equivalent to maximizing expected improvement).

Independent per-dimension factorization (what hyperopt does for flat
dict spaces like the reference's, P2/01:194-198).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

from tpuflow.tune.space import Dimension, Space, sample_space


class TPE:
    def __init__(
        self,
        n_startup_trials: int = 8,
        gamma: float = 0.25,
        n_candidates: int = 24,
        seed: int = 0,
    ):
        self.n_startup = n_startup_trials
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = np.random.default_rng(seed)

    def suggest(
        self,
        space: Space,
        history: Sequence[Tuple[Dict[str, Any], float]],
    ) -> Dict[str, Any]:
        """history: [(params, loss), ...] for completed trials."""
        done = [(p, l) for p, l in history if np.isfinite(l)]
        if len(done) < self.n_startup:
            return sample_space(space, self.rng)
        losses = np.array([l for _, l in done])
        order = np.argsort(losses, kind="stable")
        n_good = max(1, int(math.ceil(self.gamma * len(done))))
        good_idx = set(order[:n_good].tolist())
        out: Dict[str, Any] = {}
        for key, dim in space.items():
            good = [dim.to_unit(done[i][0][key]) for i in good_idx if key in done[i][0]]
            bad = [
                dim.to_unit(p[key])
                for i, (p, _) in enumerate(done)
                if i not in good_idx and key in p
            ]
            out[key] = self._suggest_dim(dim, good, bad)
        return out

    # -- per-dimension ----------------------------------------------------

    def _suggest_dim(self, dim: Dimension, good: List[float], bad: List[float]) -> Any:
        if dim.kind == "choice":
            return self._suggest_choice(dim, good, bad)
        lo, hi = dim.bounds()  # loguniform bounds are already log-space
        cands = self._parzen_samples(good, lo, hi)
        lg = self._parzen_logpdf(cands, good, lo, hi)
        lb = self._parzen_logpdf(cands, bad, lo, hi)
        best = cands[int(np.argmax(lg - lb))]
        return dim.from_unit(float(best))

    # Fixed exploration mass: the uniform prior keeps a constant share of
    # the mixture so the sampler can never collapse onto a clump of past
    # observations (the failure mode of a 1/(n+1)-decaying prior).
    _PRIOR_WEIGHT = 0.2

    def _parzen_samples(self, pts: List[float], lo: float, hi: float) -> np.ndarray:
        sigmas = self._bandwidths(pts, lo, hi)
        out = []
        for _ in range(self.n_candidates):
            if pts and self.rng.random() > self._PRIOR_WEIGHT:
                i = int(self.rng.integers(len(pts)))
                x = self.rng.normal(pts[i], sigmas[i])
                if not (lo <= x <= hi):
                    # redraw uniformly instead of clipping: clipping piles
                    # an atom of mass exactly on the bound and TPE then
                    # re-suggests the boundary forever
                    x = self.rng.uniform(lo, hi)
                out.append(x)
            else:
                out.append(self.rng.uniform(lo, hi))
        return np.array(out)

    def _parzen_logpdf(
        self, xs: np.ndarray, pts: List[float], lo: float, hi: float
    ) -> np.ndarray:
        width = max(hi - lo, 1e-12)
        prior = -math.log(width)
        if not pts:
            return np.full(len(xs), prior)
        sigmas = self._bandwidths(pts, lo, hi)[None, :]
        mus = np.asarray(pts)[None, :]
        z = (xs[:, None] - mus) / sigmas
        comp = (
            -0.5 * z * z
            - np.log(sigmas * math.sqrt(2 * math.pi))
            + math.log((1 - self._PRIOR_WEIGHT) / len(pts))
        )
        stacked = np.concatenate(
            [comp, np.full((len(xs), 1), prior + math.log(self._PRIOR_WEIGHT))],
            axis=1,
        )
        m = stacked.max(axis=1)
        return m + np.log(np.exp(stacked - m[:, None]).sum(axis=1))

    @staticmethod
    def _bandwidths(pts: List[float], lo: float, hi: float) -> np.ndarray:
        """Per-point adaptive bandwidth (hyperopt's heuristic): each
        kernel's width is the larger gap to its sorted neighbors,
        clipped to [width/min(100, n+1), width]."""
        width = max(hi - lo, 1e-12)
        n = len(pts)
        if n == 0:
            return np.array([])
        if n == 1:
            return np.array([width / 2])
        order = np.argsort(pts)
        srt = np.asarray(pts)[order]
        ext = np.concatenate([[lo], srt, [hi]])
        left = srt - ext[:-2]
        right = ext[2:] - srt
        sig_sorted = np.maximum(left, right)
        lo_clip = width / min(100.0, n + 1.0)
        sig_sorted = np.clip(sig_sorted, lo_clip, width)
        out = np.empty(n)
        out[order] = sig_sorted
        return out

    def _suggest_choice(self, dim: Dimension, good: List[float], bad: List[float]) -> Any:
        k = len(dim.options)
        gc = np.ones(k)
        for g in good:
            gc[int(g)] += 1
        bc = np.ones(k)
        for b in bad:
            bc[int(b)] += 1
        score = np.log(gc / gc.sum()) - np.log(bc / bc.sum())
        # sample from the good distribution, tilted by the ratio
        probs = gc / gc.sum() * np.exp(score)
        probs /= probs.sum()
        return dim.options[int(self.rng.choice(k, p=probs))]
