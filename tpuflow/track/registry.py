"""Model registry with stage transitions (C12, N10).

≙ the reference's registry flow: ``register_model('runs:/<id>/model',
name)`` → ``transition_model_version_stage(..., 'Production')`` → load
``models:/<name>/production`` (P2/01_hyperopt_single_machine_model.py:278-299,
repeated P2/02:417-432). Versions are monotonically numbered; a stage
transition optionally archives the versions currently in that stage
(MLflow's archive_existing_versions semantics).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from tpuflow.track.store import TrackingStore, _atomic_json

STAGES = ("None", "Staging", "Production", "Archived")


class ModelRegistry:
    def __init__(self, store: TrackingStore):
        self.store = store
        self.root = os.path.join(store.root, "registry")
        os.makedirs(self.root, exist_ok=True)

    # -- registration -----------------------------------------------------

    def register_model(self, model_uri: str, name: str) -> Dict[str, Any]:
        """Snapshot the artifact path behind ``model_uri`` as a new
        version of ``name``. Returns version metadata."""
        src = self.store.resolve_uri(model_uri)
        if not os.path.exists(src):
            raise FileNotFoundError(f"model uri {model_uri!r} -> {src} missing")
        versions = self.versions(name)
        v = (max((m["version"] for m in versions), default=0)) + 1
        vdir = self._vdir(name, v)
        os.makedirs(vdir, exist_ok=True)
        meta = {
            "name": name,
            "version": v,
            "source_uri": model_uri,
            "source_path": src,
            "stage": "None",
            "created_at": time.time(),
        }
        _atomic_json(os.path.join(vdir, "meta.json"), meta)
        return meta

    # -- stages -----------------------------------------------------------

    def transition_model_version_stage(
        self,
        name: str,
        version: int,
        stage: str,
        archive_existing_versions: bool = True,
    ) -> Dict[str, Any]:
        if stage not in STAGES:
            raise ValueError(f"stage must be one of {STAGES}")
        if archive_existing_versions and stage in ("Staging", "Production"):
            for m in self.versions(name):
                if m["stage"] == stage and m["version"] != version:
                    self._set_stage(name, m["version"], "Archived")
        return self._set_stage(name, version, stage)

    def _set_stage(self, name: str, version: int, stage: str) -> Dict[str, Any]:
        vdir = self._vdir(name, version)
        mpath = os.path.join(vdir, "meta.json")
        with open(mpath) as f:
            meta = json.load(f)
        meta["stage"] = stage
        _atomic_json(mpath, meta)
        return meta

    # -- queries ----------------------------------------------------------

    def list_models(self) -> List[str]:
        """Registered model names (a dir with a versions/ tree each)."""
        out = []
        for n in sorted(os.listdir(self.root)):
            if os.path.isdir(os.path.join(self.root, n, "versions")):
                out.append(n)
        return out

    def versions(self, name: str) -> List[Dict[str, Any]]:
        ndir = os.path.join(self.root, name, "versions")
        if not os.path.isdir(ndir):
            return []
        out = []
        for d in sorted(os.listdir(ndir), key=lambda s: int(s)):
            with open(os.path.join(ndir, d, "meta.json")) as f:
                out.append(json.load(f))
        return out

    def get_version(self, name: str, version: int) -> Dict[str, Any]:
        with open(os.path.join(self._vdir(name, version), "meta.json")) as f:
            return json.load(f)

    def latest_version(
        self, name: str, stage: Optional[str] = None
    ) -> Optional[Dict[str, Any]]:
        vs = self.versions(name)
        if stage is not None:
            vs = [m for m in vs if m["stage"].lower() == stage.lower()]
        return vs[-1] if vs else None

    def resolve_uri(self, uri: str) -> str:
        """``models:/<name>/<stage-or-version>`` → artifact filesystem path
        (≙ load_model('models:/<name>/production'), P2/01:297-299)."""
        if not uri.startswith("models:/"):
            return self.store.resolve_uri(uri)
        rest = uri[len("models:/") :]
        name, _, sel = rest.partition("/")
        if sel.isdigit():
            meta = self.get_version(name, int(sel))
        else:
            meta = self.latest_version(name, stage=sel or None)
            if meta is None:
                raise KeyError(f"no version of {name!r} in stage {sel!r}")
        return meta["source_path"]

    def _vdir(self, name: str, version: int) -> str:
        return os.path.join(self.root, name, "versions", str(version))
