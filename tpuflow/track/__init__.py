from tpuflow.track.store import Run, TrackingStore  # noqa: F401
from tpuflow.track.registry import ModelRegistry  # noqa: F401
