"""File-based experiment tracking (C11, N10) — the MLflow-tracking equivalent.

Covers what the reference actually exercises of MLflow:
- runs with params / step-stamped metrics / artifacts
  (``log_param/log_metric/log_model``, P1/03_model_training_distributed.py:363-373);
- autolog-style per-epoch metric capture (P1/02:195) via
  train.TrackingCallback;
- NESTED child runs per HPO trial, named by the param string
  (P2/02:244-260);
- re-attaching to an existing run id from another process — the
  pattern where the driver creates a run and workers log into it by
  run_uuid (P1/03:361-363, :411-415);
- ``search_runs`` filtered by parent-run tag and ordered by a metric
  (P2/01:257-261, P2/02:390-399).

Storage is a plain directory tree (JSON + JSONL): no server, works on
shared filesystems, safe under the rank-0-only write discipline.
Read-modify-write paths (params.json / meta.json) additionally take a
per-run ``fcntl`` file lock, so concurrent writers to the SAME run —
e.g. ParallelTrials threads all logging to a shared parent run — never
lose updates.
"""

from __future__ import annotations

import json
import os
import shutil
import time
import uuid
from typing import Any, Dict, List, Optional

from tpuflow.core.locks import dir_lock as _run_lock

_DEFAULT_ROOT = os.environ.get("TPUFLOW_TRACKING_DIR", "./tpuflow_runs")


class Run:
    """Handle to one run directory. Context-manager; primary-only by
    convention (callers gate on core.is_primary, ≙ hvd.rank()==0)."""

    def __init__(self, store: "TrackingStore", run_id: str):
        self.store = store
        self.run_id = run_id
        self.path = store._run_path(run_id)

    # -- lifecycle --------------------------------------------------------

    def __enter__(self) -> "Run":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.end("FAILED" if exc_type else "FINISHED")

    def end(self, status: str = "FINISHED") -> None:
        with _run_lock(self.path):
            meta = self.meta()
            meta["status"] = status
            meta["end_time"] = time.time()
            self._write_meta(meta)

    # -- logging ----------------------------------------------------------

    def log_param(self, key: str, value: Any) -> None:
        self.log_params({key: value})

    def log_params(self, params: Dict[str, Any]) -> None:
        with _run_lock(self.path):
            cur = self.params()
            cur.update({str(k): v for k, v in params.items()})
            _atomic_json(os.path.join(self.path, "params.json"), cur)

    def log_metric(self, key: str, value: float, step: int = 0) -> None:
        mdir = os.path.join(self.path, "metrics")
        os.makedirs(mdir, exist_ok=True)
        with open(os.path.join(mdir, f"{key}.jsonl"), "a") as f:
            f.write(json.dumps({"step": step, "value": float(value), "ts": time.time()}) + "\n")

    def log_metrics(self, metrics: Dict[str, float], step: int = 0) -> None:
        for k, v in metrics.items():
            self.log_metric(k, v, step)

    def log_gauges(self, prefix: "str | None" = None,
                   step: int = 0) -> None:
        """Flush the process metrics plane (tpuflow.obs.gauges —
        windowed histogram percentiles, counters, pushed gauges) into
        this run as step-stamped metrics; the MetricsLogger callback's
        epoch flush, callable directly by any driver."""
        from tpuflow.obs.gauges import snapshot_gauges

        for k, v in snapshot_gauges(prefix).items():
            v = float(v)
            if v == v:  # NaN-valued summaries have no metric meaning
                self.log_metric(k, v, step)

    def set_tag(self, key: str, value: str) -> None:
        with _run_lock(self.path):
            meta = self.meta()
            meta.setdefault("tags", {})[str(key)] = str(value)
            self._write_meta(meta)

    def log_artifact(self, local_path: str, artifact_path: str = "") -> str:
        dst_dir = os.path.join(self.path, "artifacts", artifact_path)
        os.makedirs(dst_dir, exist_ok=True)
        dst = os.path.join(dst_dir, os.path.basename(local_path))
        if os.path.isdir(local_path):
            if os.path.exists(dst):
                shutil.rmtree(dst)
            shutil.copytree(local_path, dst)
        else:
            shutil.copy2(local_path, dst)
        return dst

    def log_dict(self, d: Dict[str, Any], artifact_file: str) -> str:
        dst = os.path.join(self.path, "artifacts", artifact_file)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        _atomic_json(dst, d)
        return dst

    def artifact_path(self, artifact_path: str = "") -> str:
        return os.path.join(self.path, "artifacts", artifact_path)

    # -- reads ------------------------------------------------------------

    def meta(self) -> Dict[str, Any]:
        with open(os.path.join(self.path, "meta.json")) as f:
            return json.load(f)

    def params(self) -> Dict[str, Any]:
        p = os.path.join(self.path, "params.json")
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    def metric_history(self, key: str) -> List[Dict[str, Any]]:
        p = os.path.join(self.path, "metrics", f"{key}.jsonl")
        if not os.path.exists(p):
            return []
        with open(p) as f:
            return [json.loads(line) for line in f if line.strip()]

    def metrics(self) -> Dict[str, float]:
        """Latest value per metric key."""
        mdir = os.path.join(self.path, "metrics")
        if not os.path.isdir(mdir):
            return {}
        out = {}
        for fn in os.listdir(mdir):
            if fn.endswith(".jsonl"):
                hist = self.metric_history(fn[:-6])
                if hist:
                    out[fn[:-6]] = hist[-1]["value"]
        return out

    def _write_meta(self, meta: Dict[str, Any]) -> None:
        _atomic_json(os.path.join(self.path, "meta.json"), meta)


class TrackingStore:
    @staticmethod
    def default_root() -> str:
        """The root used when none is passed (TPUFLOW_TRACKING_DIR or
        ./tpuflow_runs) — resolvable without creating directories."""
        return _DEFAULT_ROOT

    def __init__(self, root: str = _DEFAULT_ROOT):
        self.root = os.path.abspath(root)
        os.makedirs(os.path.join(self.root, "runs"), exist_ok=True)

    # -- runs -------------------------------------------------------------

    def start_run(
        self,
        run_name: Optional[str] = None,
        experiment: str = "default",
        parent_run_id: Optional[str] = None,
        run_id: Optional[str] = None,
        nested: bool = False,
    ) -> Run:
        """Create a run — or RE-ATTACH when ``run_id`` exists already
        (the driver-creates/worker-logs pattern, P1/03:361-363)."""
        # fast path requires meta.json, not just the directory — the dir
        # appears before meta under the creation lock below, and a
        # meta-less re-attach would break the first meta() read
        if run_id is not None and os.path.exists(
            os.path.join(self._run_path(run_id), "meta.json")
        ):
            return Run(self, run_id)
        run_id = run_id or uuid.uuid4().hex[:16]
        path = self._run_path(run_id)
        os.makedirs(path, exist_ok=True)
        with _run_lock(path):
            # two workers racing start_run(run_id=X): first writer wins,
            # the loser re-attaches (driver-creates/worker-logs pattern)
            if os.path.exists(os.path.join(path, "meta.json")):
                return Run(self, run_id)
            return self._create_run(path, run_id, run_name, experiment,
                                    parent_run_id)

    def _create_run(self, path, run_id, run_name, experiment,
                    parent_run_id) -> "Run":
        meta = {
            "run_id": run_id,
            "run_name": run_name or run_id,
            "experiment": experiment,
            "parent_run_id": parent_run_id,
            "status": "RUNNING",
            "start_time": time.time(),
            "end_time": None,
            "tags": {},
        }
        if parent_run_id:
            meta["tags"]["parentRunId"] = parent_run_id
        _atomic_json(os.path.join(path, "meta.json"), meta)
        return Run(self, run_id)

    def get_run(self, run_id: str) -> Run:
        if not os.path.isdir(self._run_path(run_id)):
            raise KeyError(f"no such run: {run_id}")
        return Run(self, run_id)

    def list_runs(self, experiment: Optional[str] = None) -> List[str]:
        rdir = os.path.join(self.root, "runs")
        out = []
        for rid in sorted(os.listdir(rdir)):
            try:
                meta = Run(self, rid).meta()
            except (OSError, json.JSONDecodeError):
                continue
            if experiment is None or meta.get("experiment") == experiment:
                out.append(rid)
        return out

    def search_runs(
        self,
        filter: Optional[Dict[str, Any]] = None,
        order_by: Optional[str] = None,
        experiment: Optional[str] = None,
        max_results: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        """Query runs (≙ mlflow.search_runs, P2/01:257-261).

        ``filter``: dict of dotted keys — ``tags.parentRunId``,
        ``params.lr``, ``metrics.val_accuracy`` — matched for equality.
        ``order_by``: e.g. ``"metrics.val_accuracy DESC"``.
        Returns flat dicts with run_id/run_name/params.*/metrics.*/tags.*.
        """
        rows = []
        for rid in self.list_runs(experiment):
            run = Run(self, rid)
            meta = run.meta()
            row: Dict[str, Any] = {
                "run_id": rid,
                "run_name": meta.get("run_name"),
                "status": meta.get("status"),
                "parent_run_id": meta.get("parent_run_id"),
            }
            for k, v in meta.get("tags", {}).items():
                row[f"tags.{k}"] = v
            for k, v in run.params().items():
                row[f"params.{k}"] = v
            for k, v in run.metrics().items():
                row[f"metrics.{k}"] = v
            rows.append(row)
        if filter:
            def keep(row):
                for k, v in filter.items():
                    if str(row.get(k)) != str(v):
                        return False
                return True

            rows = [r for r in rows if keep(r)]
        if order_by:
            parts = order_by.split()
            key = parts[0]
            desc = len(parts) > 1 and parts[1].upper() == "DESC"
            present = [r for r in rows if r.get(key) is not None]
            absent = [r for r in rows if r.get(key) is None]
            present.sort(key=lambda r: r[key], reverse=desc)
            rows = present + absent  # missing metric always ranks last
        if max_results:
            rows = rows[:max_results]
        return rows

    # -- uris -------------------------------------------------------------

    def resolve_uri(self, uri: str) -> str:
        """``runs:/<run_id>/<artifact_path>`` → filesystem path
        (``models:/...`` URIs resolve via ModelRegistry)."""
        if uri.startswith("runs:/"):
            rest = uri[len("runs:/") :]
            run_id, _, apath = rest.partition("/")
            return self.get_run(run_id).artifact_path(apath)
        if os.path.exists(uri):
            return uri
        raise ValueError(f"cannot resolve uri {uri!r}")

    def _run_path(self, run_id: str) -> str:
        return os.path.join(self.root, "runs", run_id)


def _atomic_json(path: str, obj: Any) -> None:
    import tempfile

    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d)
    with os.fdopen(fd, "w") as f:
        json.dump(obj, f, indent=1, default=str)
    os.replace(tmp, path)
