from tpuflow.train.trainer import Trainer  # noqa: F401
from tpuflow.train.lm import LMTrainer  # noqa: F401
from tpuflow.train.pipeline_trainer import PipelineTrainer  # noqa: F401
from tpuflow.train.state import TrainState  # noqa: F401
from tpuflow.train.lr import LRController  # noqa: F401
from tpuflow.train.callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    History,
    ModelCheckpoint,
    ReduceLROnPlateau,
    SystemMetricsCallback,
    TrackingCallback,
)
from tpuflow.train.optimizers import (  # noqa: F401
    available_optimizers,
    get_optimizer,
)
