"""Optimizer factory: selection by name (C14).

≙ the reference's reflection over Keras optimizers,
``getattr(tf.keras.optimizers, params['optimizer'])(lr)``
(P2/01_hyperopt_single_machine_model.py:154-155) — needed so HPO can
search over the optimizer choice. Frozen-backbone masking applies zero
updates to backbone params (≙ Keras layer.trainable=False).

The learning rate is wrapped with ``optax.inject_hyperparams`` so
callbacks can adjust it at runtime (warmup, ReduceLROnPlateau) without
recompiling — the TPU-native form of Keras LR callbacks.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import optax

# Case-insensitive registry; Keras-style names included.
_OPTIMIZERS: Dict[str, Callable[..., optax.GradientTransformation]] = {
    "adam": optax.adam,
    "adamw": optax.adamw,
    "adadelta": optax.adadelta,
    "adagrad": optax.adagrad,
    "sgd": optax.sgd,
    "rmsprop": optax.rmsprop,
    "lamb": optax.lamb,
    # LARS: the layer-wise adaptive rate classic for large-batch CNN
    # training — the principled companion to the b512 batch probes
    # (LR x N alone degrades as the global batch grows)
    "lars": optax.lars,
    "lion": optax.lion,
    "nadam": optax.nadam,
}


def available_optimizers() -> list:
    return sorted(_OPTIMIZERS)


def get_optimizer(
    name: str,
    learning_rate: float,
    param_mask: Optional[Any] = None,
    grad_clip_norm: Optional[float] = None,
    **kwargs,
) -> optax.GradientTransformation:
    """Build an optimizer by name with a runtime-adjustable LR.

    ``param_mask``: pytree of bools, True = trainable. Frozen leaves get
    ``optax.set_to_zero`` — structurally zero updates, and crucially zero
    *optimizer state*, so frozen-backbone training carries no Adam
    moments for the backbone (the ZeRO-ish memory win of masking).

    ``grad_clip_norm``: if set, gradients are clipped to this GLOBAL
    norm before the update (optax.clip_by_global_norm chained in front;
    the LR-steering helpers below see through the chain state).
    """
    key = name.lower()
    if key not in _OPTIMIZERS:
        raise ValueError(
            f"unknown optimizer {name!r}; available: {available_optimizers()}"
        )
    tx = optax.inject_hyperparams(_OPTIMIZERS[key])(
        learning_rate=learning_rate, **kwargs
    )
    if grad_clip_norm is not None:
        if grad_clip_norm <= 0:
            raise ValueError(f"grad_clip_norm must be > 0, got {grad_clip_norm}")
        tx = optax.chain(optax.clip_by_global_norm(grad_clip_norm), tx)
    if param_mask is not None:
        tx = optax.multi_transform(
            {"train": tx, "frozen": optax.set_to_zero()},
            param_labels=lambda params: _labels_from_mask(param_mask),
        )
    return tx


def _labels_from_mask(mask: Any) -> Any:
    import jax

    return jax.tree.map(lambda t: "train" if t else "frozen", mask)


def set_learning_rate(opt_state: Any, lr: float) -> Any:
    """Return opt_state with the injected learning_rate leaf replaced.

    Works through the optional multi_transform wrapper. This is how
    warmup/plateau callbacks steer the LR between steps (≙ Keras
    callbacks mutating optimizer.lr) — a 4-byte update, no recompile.
    """
    import jax.numpy as jnp

    def _replace(s):
        if isinstance(s, optax.InjectStatefulHyperparamsState) or hasattr(
            s, "hyperparams"
        ):
            hp = dict(s.hyperparams)
            hp["learning_rate"] = jnp.asarray(lr, jnp.float32)
            return s._replace(hyperparams=hp)
        if type(s) is tuple:  # optax.chain state (e.g. grad clipping)
            return tuple(_replace(x) for x in s)
        return s

    if hasattr(opt_state, "inner_states"):  # multi_transform wrapper
        inner = dict(opt_state.inner_states)
        inner["train"] = _map_masked_node(inner["train"], _replace)
        return opt_state._replace(inner_states=inner)
    return _replace(opt_state)


def get_learning_rate(opt_state: Any) -> float:
    def _find(s):
        if hasattr(s, "hyperparams"):
            return float(s.hyperparams["learning_rate"])
        if type(s) is tuple:  # chain state: search the elements
            for x in s:
                got = _find(x)
                if got is not None:
                    return got
        return None

    if hasattr(opt_state, "inner_states"):
        node = opt_state.inner_states["train"]
        node = node.inner_state if hasattr(node, "inner_state") else node
        got = _find(node)
    else:
        got = _find(opt_state)
    if got is None:
        raise ValueError(
            "opt_state carries no inject_hyperparams learning_rate leaf "
            "(was it built by get_optimizer?)"
        )
    return got


def _map_masked_node(node: Any, fn: Callable[[Any], Any]) -> Any:
    if hasattr(node, "inner_state"):  # MaskedState
        return node._replace(inner_state=fn(node.inner_state))
    return fn(node)
