"""First-class long-context LM training.

BEYOND-REFERENCE capability (SURVEY.md §5.7: the reference has no
attention and no sequence axis — its only long-input story is dataset
streaming). ``LMTrainer`` packages the recipe demonstrated raw in
examples/08_long_context_lm.py as a peer of the image ``Trainer``:

- **Mesh**: ``data`` (batch) × optional ``seq`` (context) axes. With a
  ``seq_axis`` model, attention runs as ring attention — K/V shards
  rotating over ICI (tpuflow.parallel.ring_attention) — and per-device
  memory is O(seq / sp), the linear-context-scaling recipe.
- **Collectives**: the forward is a ``shard_map`` over the mesh; loss
  and grads are taken on the gathered logits under ``jit``, so XLA's
  partitioner inserts the data-axis all-reduce (no hand-written pmean —
  contrast tpuflow.train.trainer, which keeps the manual-pmean DP path
  for reference parity with Horovod, SURVEY.md §5.8).
- **Shared machinery**: TrainState, optimizer-by-name with runtime LR
  (tpuflow.train.optimizers), LR warmup × world-size scaling
  (tpuflow.train.lr — ≙ P1/03:300-318 applied to a new model family),
  atomic checkpoint/resume (tpuflow.ckpt), tracking-store logging and
  rank-0 side-effect discipline (≙ P1/03:360-373).

Token batches are plain int32 arrays ``(batch, seq_len)`` — the LM has
no decode/augmentation plane, so there is no converter/loader layer in
between (corpus tokenization is upstream of this framework).
"""

from __future__ import annotations

import time

from typing import Callable, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from tpuflow.core.compat import shard_map
from jax.sharding import PartitionSpec as P

from tpuflow.ckpt.checkpoint import (
    checkpoint_number,
    latest_checkpoint,
    restore_into_state,
    save_checkpoint,
)
from tpuflow.core.config import TrainConfig
from tpuflow.core.dist import is_primary
from tpuflow.data.tokens import TokenDataset
from tpuflow.models.transformer import TransformerLM, next_token_loss
from tpuflow.obs import memory as _mem
from tpuflow.obs import trace
from tpuflow.obs.executables import registered_jit as _registered_jit
from tpuflow.parallel.mesh import DATA_AXIS, MODEL_AXIS, build_nd_mesh
from tpuflow.train.lr import LRController
from tpuflow.train.optimizers import get_optimizer, set_learning_rate
from tpuflow.train.state import TrainState


class LMTrainer:
    """Data- (and optionally sequence-) parallel causal-LM trainer.

    ``model.seq_axis`` decides the topology: ``None`` → pure DP over the
    mesh's ``data`` axis; a named axis (e.g. ``"seq"``) → the mesh must
    carry that axis too and tokens are sharded along it (ring
    attention). ``batch_size`` in :meth:`fit` is GLOBAL (the whole mesh
    consumes one batch per step).
    """

    def __init__(
        self,
        model: TransformerLM,
        config: Optional[TrainConfig] = None,
        mesh=None,
        devices=None,
        zero: Optional[str] = None,
    ):
        self.model = model
        self.cfg = config or TrainConfig()
        if mesh is None:
            n = len(devices) if devices is not None else len(jax.devices())
            axes = {DATA_AXIS: n}
            if model.seq_axis is not None:
                axes = {DATA_AXIS: 1, model.seq_axis: n}
            elif zero is not None or model.n_experts > 0:
                # GSPMD state shardings reference the LM's 'model'
                # annotations — a size-1 model axis keeps them valid
                # for pure-ZeRO / dense-MoE use on a data-only topology
                # (expert-SHARDED MoE needs an explicit mesh carrying
                # the expert axis)
                axes = {DATA_AXIS: n, MODEL_AXIS: 1}
            mesh = build_nd_mesh(axes, devices=devices)
        self.mesh = mesh
        if model.seq_axis is not None and model.seq_axis not in mesh.axis_names:
            raise ValueError(
                f"model.seq_axis={model.seq_axis!r} not in mesh axes "
                f"{mesh.axis_names}"
            )
        self.world = mesh.shape[DATA_AXIS] if DATA_AXIS in mesh.axis_names else 1
        self.sp = (
            mesh.shape[model.seq_axis] if model.seq_axis is not None else 1
        )
        # GSPMD mode: a 'model' mesh axis (tensor parallelism over the
        # LM's nn.with_partitioning annotations — Megatron-style qkv/
        # mlp column+row sharding, vocab-sharded embed/head) and/or
        # ZeRO-sharded optimizer state. Mutually exclusive with manual
        # sequence parallelism: ring attention runs inside shard_map,
        # where GSPMD's auto-partitioner has no say.
        if zero not in (None, "zero1", "fsdp"):
            raise ValueError(f"zero must be None|'zero1'|'fsdp', got {zero!r}")
        self.zero = zero
        self.tp = (
            mesh.shape[MODEL_AXIS] if MODEL_AXIS in mesh.axis_names else 1
        )
        # MoE LMs also route through GSPMD: expert-sharded params are
        # plain partitioning annotations (dryrun EP case), and the
        # load-balance aux loss needs the mutable 'losses' collection
        # that the manual shard_map fwd does not thread.
        self._gspmd = (
            self.tp > 1 or zero is not None or model.n_experts > 0
        )
        if model.n_experts > 0 and model.seq_axis is not None:
            raise ValueError(
                "MoE (n_experts>0) and seq_axis cannot combine in "
                "LMTrainer: experts ride GSPMD, ring attention rides "
                "shard_map"
            )
        if (
            model.ep_axis is not None
            and model.ep_axis not in mesh.axis_names
        ):
            raise ValueError(
                f"ep_axis={model.ep_axis!r} not in mesh axes "
                f"{mesh.axis_names}"
            )
        if self._gspmd and model.seq_axis is not None:
            raise ValueError(
                "tensor-parallel/ZeRO (GSPMD) and seq_axis (manual ring "
                "attention) cannot combine in LMTrainer; shard long "
                "contexts with seq_axis alone or shard weights with a "
                "model axis alone"
            )
        if self._gspmd and MODEL_AXIS not in mesh.axis_names:
            why = (
                f"zero={zero!r}" if zero is not None
                else f"MoE (n_experts={model.n_experts})"
            )
            raise ValueError(
                f"{why} routes LMTrainer through GSPMD, which needs a "
                f"mesh with a '{MODEL_AXIS}' axis (size 1 is fine): the "
                "LM's partitioning annotations name it — e.g. "
                "build_nd_mesh({'data': n, 'model': 1})"
            )
        self._state_shardings = None
        self.state: Optional[TrainState] = None
        self.tx = None
        self._train_step = None
        self._eval_step = None
        self.lr_controller: Optional[LRController] = None
        self._initial_epoch = 0
        self._async_ckpt = None  # lazy AsyncCheckpointer (cfg.async_checkpoint)
        self._flops_per_step: Optional[float] = None  # XLA cost analysis
        self.health = None  # HealthMonitor, armed per-fit (cfg.watchdog)

    # ---- initialization --------------------------------------------------

    def init_state(self, rng_seed: Optional[int] = None) -> TrainState:
        seed = self.cfg.seed if rng_seed is None else rng_seed
        self.tx = get_optimizer(
            self.cfg.optimizer,
            self.cfg.learning_rate,
            grad_clip_norm=self.cfg.grad_clip_norm,
            **self.cfg.optimizer_kwargs,
        )
        if self._gspmd:
            return self._init_state_gspmd(seed)
        # init via the seq_axis=None twin: identical param tree (the
        # named axis matters only inside shard_map at apply time), and
        # it needs no mesh — same trick as examples/08.
        plain = (
            self.model.clone(seq_axis=None)
            if self.model.seq_axis is not None
            else self.model
        )
        toks0 = jnp.zeros((1, 8), jnp.int32)
        params = nn.unbox(plain.init({"params": jax.random.key(seed)}, toks0))[
            "params"
        ]
        state = TrainState(
            step=jnp.asarray(0, jnp.int32),
            params=params,
            batch_stats={},
            opt_state=self.tx.init(params),
            rng=jax.random.key(seed),
            plateau_factor=jnp.asarray(1.0, jnp.float32),
        )
        # commit the replicated placement explicitly (matches the
        # shard_map step's P() state spec). Leaving leaves uncommitted
        # happened to work for fresh fits, but restore_into_state maps
        # the checkpoint onto the TEMPLATE's shardings — an uncommitted
        # template commits the restored state to ONE device and the
        # first multi-device step then fails on conflicting committed
        # placements (surfaced by the r05 preemption-resume test).
        from tpuflow.parallel.mesh import replicate_tree

        self.state = replicate_tree(state, self.mesh)
        self._tag_state()
        return self.state

    @staticmethod
    def _aot_cost(rjit, compiled) -> dict:
        """Cost analysis of an executable ``rjit.aot_compile`` just
        built: reuse the dict the ARMED registry captured during
        registration; analyze directly only when the registry is
        disarmed (so XLA's analysis never runs twice, and a failing
        backend bumps compile.cost_analysis_errors_total once)."""
        from tpuflow.obs.executables import site_cost
        from tpuflow.obs.mfu import cost_analysis_of

        return site_cost(rjit.key) or cost_analysis_of(compiled)

    def _tag_state(self) -> None:
        """Device-buffer ledger tags (ISSUE 7): params/opt_state by
        component. Donation replaces the state's arrays every step, so
        the fit loop re-tags at epoch boundaries."""
        if self.state is None:
            return
        _mem.tag("params", {"params": self.state.params,
                            "batch_stats": self.state.batch_stats})
        _mem.tag("opt_state", self.state.opt_state)

    def _init_state_gspmd(self, seed: int) -> TrainState:
        """Sharded-state init: param specs from the LM's
        ``nn.with_partitioning`` metadata; optimizer moments inherit
        their parameter's spec, ZeRO additionally splits them (or the
        params too, for fsdp) over the data axis — same machinery as
        SpmdTrainer (tpuflow.train.spmd)."""
        from tpuflow.train.spmd import derive_state_shardings

        toks0 = jnp.zeros((1, 8), jnp.int32)

        def make_state(rng):
            params = nn.unbox(self.model.init({"params": rng}, toks0))[
                "params"
            ]
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                params=params,
                batch_stats={},
                opt_state=self.tx.init(params),
                rng=jax.random.key(seed),
                plateau_factor=jnp.ones((), jnp.float32),
            )

        boxed = jax.eval_shape(
            lambda r: self.model.init({"params": r}, toks0),
            jax.random.key(seed),
        )
        abstract = jax.eval_shape(make_state, jax.random.key(seed))
        self._state_shardings = derive_state_shardings(
            self.mesh, boxed, abstract, self.world, self.zero
        )
        self.state = _registered_jit(
            make_state, key="lm.init_state",
            out_shardings=self._state_shardings,
        )(jax.random.key(seed))
        self._tag_state()
        return self.state

    # ---- steps -----------------------------------------------------------

    def _token_spec(self):
        if self.model.seq_axis is not None:
            return P(DATA_AXIS, self.model.seq_axis)
        return P(DATA_AXIS)

    def _put(self, toks_np: np.ndarray):
        """Process-local token rows → global batch-sharded array (same
        idiom as Trainer._put: every process contributes its slice of
        the global batch; with one process this is a plain device_put).
        Multi-process sequence sharding requires the ``seq`` axis to
        live within each process's addressable devices — the normal
        topology (DP across hosts, SP inside a host/slice on ICI)."""
        from jax.sharding import NamedSharding

        n_data = self.mesh.shape.get(DATA_AXIS, 1)
        global_rows = toks_np.shape[0] * jax.process_count()
        if global_rows % n_data:
            raise ValueError(
                f"global batch {global_rows} not divisible by mesh data "
                f"axis {n_data}; choose batch_size as a multiple of "
                f"{n_data}"
            )
        if toks_np.shape[1] % self.sp:
            raise ValueError(
                f"seq_len {toks_np.shape[1]} not divisible by the "
                f"sequence-parallel degree {self.sp}"
            )
        sharding = NamedSharding(self.mesh, self._token_spec())
        return jax.make_array_from_process_local_data(
            sharding, np.ascontiguousarray(toks_np, dtype=np.int32)
        )

    def _put_block(self, rows_list):
        """K stacked local token batches → one global (K, batch, seq)
        block for the superstep scan; dim 0 is the step axis (never
        sharded), dims 1+ shard exactly like a ``_put`` batch."""
        from jax.sharding import NamedSharding

        blk = np.stack([
            np.ascontiguousarray(r, dtype=np.int32) for r in rows_list
        ])
        n_data = self.mesh.shape.get(DATA_AXIS, 1)
        global_rows = blk.shape[1] * jax.process_count()
        if global_rows % n_data:
            raise ValueError(
                f"global batch {global_rows} not divisible by mesh data "
                f"axis {n_data}; choose batch_size as a multiple of "
                f"{n_data}"
            )
        if blk.shape[2] % self.sp:
            raise ValueError(
                f"seq_len {blk.shape[2]} not divisible by the "
                f"sequence-parallel degree {self.sp}"
            )
        sharding = NamedSharding(self.mesh, P(None, *self._token_spec()))
        return jax.make_array_from_process_local_data(sharding, blk)

    def _make_steps(self) -> None:
        model = self.model
        mesh = self.mesh
        out_shardings = None

        packed_eos = self.cfg.packed_eos_id
        if packed_eos is not None and model.seq_axis is not None:
            raise ValueError(
                "packed_eos_id (sequence packing) cannot combine with "
                "seq_axis (ring attention) yet — pack shorter rows or "
                "drop sequence parallelism"
            )
        if packed_eos is not None:
            from tpuflow.models.transformer import packed_segments

        fused = bool(self.cfg.fused_loss)
        if fused and getattr(model, "tie_embeddings", False):
            raise ValueError(
                "fused_loss cannot combine with tie_embeddings yet: the "
                "vocab-chunked scan consumes a (dim, vocab) head kernel "
                "and the tied head is the transposed embedding table — "
                "drop one of the two"
            )
        if fused:
            if self._gspmd and self.tp > 1:
                raise ValueError(
                    "fused_loss needs a replicated LM head; it cannot "
                    "combine with tensor parallelism (the vocab-chunked "
                    "scan conflicts with the column-sharded kernel) — "
                    "drop fused_loss or set model axis size 1"
                )
            from tpuflow.ops.xent import fused_linear_token_loss

            # identical param tree (LMHead still creates 'kernel');
            # apply returns the final-norm hidden states instead
            model_h = model.clone(skip_head=True)

            def _fused(p, hidden, targets, mask, ls):
                return fused_linear_token_loss(
                    hidden, p["lm_head"]["kernel"], targets, mask=mask,
                    label_smoothing=ls,
                )

        def _shifted_loss(p, out, tokens, ls, tmask=None):
            """The next-token tail shared by every non-striped path:
            ``out`` is logits (plain) or hidden states (fused);
            ``tmask`` excludes cross-document targets in packed mode."""
            if fused:
                return _fused(p, out[:, :-1], tokens[:, 1:], tmask, ls)
            if tmask is not None:
                from tpuflow.models.transformer import token_loss

                return token_loss(out[:, :-1], tokens[:, 1:], mask=tmask,
                                  label_smoothing=ls)
            return next_token_loss(out, tokens, label_smoothing=ls)

        if self._gspmd:
            # GSPMD: ONE jitted program over the (data, model[, expert])
            # mesh — XLA's partitioner inserts the data-axis grad
            # all-reduce, the TP all-gathers/reduce-scatters around the
            # sharded matmuls, the expert all-to-alls, and ZeRO's
            # scatter/gather around the update.
            def loss_of(p, tokens, train):
                ls = self.cfg.label_smoothing if train else 0.0
                net = model_h if fused else model
                kw, tmask = {}, None
                if packed_eos is not None:
                    seg, pos, tmask = packed_segments(tokens, packed_eos)
                    kw = dict(segment_ids=seg, positions=pos)
                if model.n_experts > 0 and train:
                    # MoE training: LM loss + the routers' load-balance
                    # aux losses (sown into the mutable 'losses'
                    # collection by tpuflow.models.moe)
                    out, coll = net.apply(
                        {"params": p}, tokens, train=True,
                        mutable=["losses"], **kw,
                    )
                    aux = sum(
                        jnp.sum(a)
                        for a in jax.tree.leaves(coll.get("losses", {}))
                    )
                    return _shifted_loss(p, out, tokens, ls, tmask) + aux
                out = net.apply({"params": p}, tokens, train=train, **kw)
                return _shifted_loss(p, out, tokens, ls, tmask)

            out_shardings = (self._state_shardings, None)
        else:
            net = model_h if fused else model
            if packed_eos is not None:
                # packing metadata is row-local, so it shards exactly
                # like the tokens and rides through the shard_map
                fwd_packed = shard_map(
                    lambda p, t, seg, pos, train: net.apply(
                        {"params": p}, t, train=train,
                        segment_ids=seg, positions=pos,
                    ),
                    mesh=mesh,
                    in_specs=(P(), self._token_spec(),
                              self._token_spec(), self._token_spec(),
                              P()),
                    out_specs=P(DATA_AXIS, None, None),
                )
            fwd = shard_map(
                lambda p, t, train: net.apply(
                    {"params": p}, t, train=train
                ),
                mesh=mesh,
                in_specs=(P(), self._token_spec(), P()),
                out_specs=(
                    P(DATA_AXIS, model.seq_axis, None)
                    if model.seq_axis is not None
                    else P(DATA_AXIS, None, None)
                ),
            )

            striped = (
                model.seq_axis is not None
                and model.sp_layout == "striped"
            )

            def loss_of(p, tokens, train):
                # loss over the GLOBAL gathered logits: the next-token
                # shift crosses sequence-shard boundaries, so it must
                # happen outside the shard_map (next_token_loss doc).
                # Striped layout: tokens go to the model in round-robin
                # shard order (balanced causal ring) and the LOGITS STAY
                # striped — only the int32 targets are gathered into
                # striped alignment (vocab-times smaller than
                # un-permuting the (B, S, vocab) logits across the
                # sequence shards). Striped position i holds logical
                # token perm[i], whose target is logical token
                # perm[i]+1; the final logical position is masked out
                # (shapes are static → the index maps are trace-time
                # constants).
                ls = self.cfg.label_smoothing if train else 0.0
                if striped:
                    from tpuflow.models.transformer import token_loss
                    from tpuflow.parallel.ring_attention import (
                        striped_permutation,
                    )

                    s = tokens.shape[1]
                    perm = striped_permutation(s, self.sp)
                    out = fwd(
                        p, jnp.take(tokens, perm, axis=1), train
                    )
                    tgt_pos = np.minimum(perm + 1, s - 1)
                    targets = jnp.take(tokens, tgt_pos, axis=1)
                    valid = jnp.asarray(
                        (perm + 1 < s).astype(np.float32)
                    )[None, :]
                    if fused:
                        return _fused(p, out, targets, valid, ls)
                    return token_loss(
                        out, targets, mask=valid, label_smoothing=ls
                    )
                if packed_eos is not None:
                    seg, pos, tmask = packed_segments(tokens, packed_eos)
                    out = fwd_packed(p, tokens, seg, pos, train)
                    return _shifted_loss(p, out, tokens, ls, tmask)
                out = fwd(p, tokens, train)
                return _shifted_loss(p, out, tokens, ls)

        accum = max(1, int(self.cfg.grad_accum_steps))
        # watchdog mode (ISSUE 5): grad-norm + a non-finite flag join
        # the step's metrics block ON DEVICE, so the health monitor's
        # guard rides the fetch that already happens — zero extra
        # syncs. Off by default: the global-norm reduction changes the
        # compiled program, and parity-pinned runs must stay bitwise.
        watch = bool(getattr(self.cfg, "watchdog", False))

        def _health_metrics(loss, grads):
            gn = optax.global_norm(grads)
            bad = jnp.logical_not(
                jnp.isfinite(loss) & jnp.isfinite(gn)
            ).astype(jnp.float32)
            return {"grad_norm": gn, "nonfinite": bad}

        def train_step(state: TrainState, tokens, lr):
            if accum == 1:
                loss, grads = jax.value_and_grad(
                    lambda p: loss_of(p, tokens, True)
                )(state.params)
            else:
                # gradient accumulation: sequential micro-steps against
                # FIXED params, gradients averaged before one update —
                # exactly the unaccumulated step for mean losses (equal
                # micro sizes), with peak activation memory divided by
                # `accum`
                b = tokens.shape[0]
                if b % accum:
                    raise ValueError(
                        f"batch {b} not divisible by "
                        f"grad_accum_steps={accum}"
                    )
                if (b // accum) % max(1, self.world):
                    raise ValueError(
                        f"micro-batch {b // accum} rows must divide by "
                        f"the data axis {self.world}; pick batch/accum "
                        "as a multiple of it"
                    )
                micro = tokens.reshape(accum, b // accum, tokens.shape[1])

                def body(carry, t):
                    loss_sum, gacc = carry
                    l, g = jax.value_and_grad(
                        lambda p: loss_of(p, t, True)
                    )(state.params)
                    return (
                        loss_sum + l,
                        jax.tree.map(jnp.add, gacc, g),
                    ), None

                (loss_sum, gsum), _ = jax.lax.scan(
                    body,
                    (jnp.zeros((), jnp.float32),
                     jax.tree.map(jnp.zeros_like, state.params)),
                    micro,
                )
                loss = loss_sum / accum
                grads = jax.tree.map(lambda g: g / accum, gsum)
            metrics = {"loss": loss}
            if watch:
                metrics.update(_health_metrics(loss, grads))
            opt_state = set_learning_rate(state.opt_state, lr)
            updates, opt_state = self.tx.update(
                grads, opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            new_state = state.replace(
                step=state.step + 1, params=params, opt_state=opt_state
            )
            return new_state, metrics

        def eval_step(state: TrainState, tokens):
            return {"loss": loss_of(state.params, tokens, False)}

        if out_shardings is not None:
            self._train_step = _registered_jit(
                train_step, key="lm.train_step", donate_argnums=0,
                out_shardings=out_shardings,
            )
        else:
            self._train_step = _registered_jit(
                train_step, key="lm.train_step", donate_argnums=0
            )
        self._eval_step = _registered_jit(eval_step, key="lm.eval_step")
        self._build_superstep(train_step, out_shardings)

    def _build_superstep(self, train_step, out_shardings=None) -> None:
        """Superstep program (cfg.superstep > 1): K chained train steps
        in ONE jitted ``lax.scan`` over a stacked (K, batch, seq) token
        block — one host dispatch per K steps, per-step losses stacked
        into a device-resident (K,) block. The body is the SAME
        ``train_step`` the per-step path jits, so per-step losses match
        the K=1 loop — bitwise under a fixed compilation config
        (tests/test_superstep.py).
        Shared with PipelineTrainer, whose schedules all expose the same
        ``(state, tokens, lr) -> (state, metrics)`` pure step. Tracing
        is lazy — K=1 runs never touch this."""

        def superstep(state, tokens, lrs):
            def body(c, x):
                t, lr = x
                return train_step(c, t, lr)

            return jax.lax.scan(body, state, (tokens, lrs))

        if out_shardings is not None:
            self._superstep = _registered_jit(
                superstep, key="lm.superstep", donate_argnums=0,
                out_shardings=out_shardings,
            )
        else:
            self._superstep = _registered_jit(
                superstep, key="lm.superstep", donate_argnums=0
            )

    # ---- checkpoint / resume --------------------------------------------

    def maybe_resume(self, checkpoint_dir: Optional[str],
                     steps_per_epoch: Optional[int] = None) -> int:
        """Restore the newest checkpoint if one exists; returns the
        epoch to continue from (0 when starting fresh).

        With ``steps_per_epoch``, mid-epoch PREEMPTION checkpoints
        (``checkpoint-step-{N}.ckpt``, cfg.checkpoint_on_preempt) are
        also considered, compared in global-step units; when one is
        newest the position within the epoch is stashed as
        ``self._resume_skip_steps`` and the next :meth:`fit`
        fast-forwards to it — EXACT resume (the deterministic
        (seed, epoch) batch order makes the skipped prefix
        reproducible). Without it, step checkpoints are ignored."""
        self._resume_skip_steps = 0
        if not checkpoint_dir:
            return 0
        if steps_per_epoch is not None:
            from tpuflow.ckpt.checkpoint import latest_resume_point

            found = latest_resume_point(checkpoint_dir,
                                        int(steps_per_epoch))
            if found is None:
                return 0
            path, epoch, skip = found
            if self.state is None:
                self.init_state()
            self.state = restore_into_state(path, self.state)
            self._resume_skip_steps = skip
            self._resume_epoch = epoch
            self._initial_epoch = epoch
            if is_primary():
                print(f"resumed from {path} (epoch {epoch}, +{skip} steps)")
            return epoch
        path = latest_checkpoint(checkpoint_dir)
        if path is None:
            return 0
        if self.state is None:
            self.init_state()
        self.state = restore_into_state(path, self.state)
        step = int(self.state.step)
        self._initial_epoch = checkpoint_number(path)
        if is_primary():
            print(f"resumed from {path} (step {step})")
        return self._initial_epoch

    # ---- elastic resize (ISSUE 10) ---------------------------------------

    def _resize_world(self, new_world: int) -> None:
        """Rebuild the trainer for ``new_world`` data-parallel replicas
        IN-PROCESS (single-controller elastic resize at a block
        boundary): snapshot the full state to host, rebuild the mesh
        with the resized data axis over the process's own devices,
        re-derive shardings by re-running init on the new mesh, then
        place the snapshot back under the new layout
        (``sharded.place_state_dict`` — the in-memory twin of the
        on-disk sharded restore). Compiled executables are invalidated
        — a resize is a recompile by construction; the multi-process
        path (gang membership changes) instead persists a sharded
        checkpoint and exits for the relauncher (see fit)."""
        from tpuflow.ckpt.sharded import host_state_dict, place_state_dict

        if self.model.seq_axis is not None:
            raise ValueError(
                "elastic resize with sequence parallelism is not "
                "supported: the ring-attention degree is part of the "
                "model's math, not just its layout"
            )
        host = host_state_dict(self.state)
        axes = {
            name: (int(new_world) if name == DATA_AXIS
                   else int(self.mesh.shape[name]))
            for name in self.mesh.axis_names
        }
        need = int(np.prod(list(axes.values())))
        devices = list(jax.devices())
        if need > len(devices):
            raise ValueError(
                f"elastic resize to world={new_world} needs {need} "
                f"devices, have {len(devices)}"
            )
        self.mesh = build_nd_mesh(axes, devices=devices[:need])
        self.world = int(new_world)
        # re-init on the new mesh: re-derives _state_shardings (GSPMD)
        # / the replicated template, then the snapshot overwrites every
        # value — including step/rng, so training continues, not
        # restarts
        self.init_state()
        self.state = place_state_dict(host, self.state)
        self._tag_state()
        self._train_step = None
        self._eval_step = None
        self._step_exec = None
        self._sstep_execs = {}
        self._flops_per_step = None
        self._make_steps()

    # ---- fit -------------------------------------------------------------

    def _local_slice(self, batch_size: int) -> Tuple[int, int]:
        """(rows per process, this process's slice index) for a GLOBAL
        batch — derived from the TOKEN SHARDING's addressable row
        ranges, not from process_count: with a replicated or
        partially-replicated row dimension (pure PP; DP x PP whose pipe
        axis crosses processes) several processes must feed the SAME
        rows, and feeding per-process slices instead would silently
        diverge the "replicated" global array across hosts."""
        from jax.sharding import NamedSharding

        spec = self._token_spec()
        row_spec = P(spec[0]) if len(spec) else P()
        n_rows_shards = (
            self.mesh.shape.get(spec[0], 1)
            if len(spec) and spec[0] is not None else 1
        )
        if batch_size % n_rows_shards:
            raise ValueError(
                f"global batch {batch_size} not divisible by mesh data "
                f"axis {n_rows_shards}; choose batch_size as a multiple "
                f"of {n_rows_shards}"
            )
        sharding = NamedSharding(self.mesh, row_spec)
        idx_map = sharding.addressable_devices_indices_map((batch_size,))
        starts = [sl[0].start or 0 for sl in idx_map.values()]
        stops = [
            batch_size if sl[0].stop is None else sl[0].stop
            for sl in idx_map.values()
        ]
        start, stop = min(starts), max(stops)
        b_local = stop - start
        if b_local <= 0 or batch_size % b_local or start % b_local:
            raise ValueError(
                f"global batch_size={batch_size} does not tile this "
                f"topology's addressable row range [{start}, {stop}); "
                f"choose a batch divisible by "
                f"{batch_size // max(1, b_local)} feed groups"
            )
        return b_local, start // b_local

    def _expected_shard(self) -> Tuple[int, int]:
        """(cur, count) a TokenDataset must be sharded as for this
        trainer's token layout: one shard per distinct process row-range
        (== process_count for pure DP; 1 for a replicated pure-PP
        feed)."""
        probe = max(1, self.mesh.shape.get(DATA_AXIS, 1)) * max(
            1, jax.process_count()
        )
        b_local, idx = self._local_slice(probe)
        return idx, probe // b_local

    def _eval_mean_loss(
        self, tokens: "np.ndarray | TokenDataset", batch_size: int
    ) -> Optional[float]:
        """Mean eval loss over all full global batches (None if there is
        not even one). Shared by fit()'s val path and evaluate().
        Accepts a :class:`TokenDataset` (its own ``batch_rows`` governs;
        epoch 0 of the deterministic stream is evaluated)."""
        if isinstance(tokens, TokenDataset):
            want_cur, want_count = self._expected_shard()
            if (tokens.cur_shard, tokens.shard_count) != (
                want_cur, want_count
            ):
                raise ValueError(
                    f"eval TokenDataset shard "
                    f"({tokens.cur_shard}/{tokens.shard_count}) does not "
                    f"match the expected ({want_cur}/{want_count})"
                )
            with trace.span("train.eval", phase="eval"):
                losses = []
                for b in tokens.iter_epoch(0):
                    t = self._put(b)
                    _mem.tag("eval", t)
                    losses.append(
                        self._eval_step(self.state, t)["loss"]
                    )
                return (
                    float(jnp.mean(jnp.stack(losses))) if losses else None
                )
        b_local, proc = self._local_slice(batch_size)
        losses = []
        with trace.span("train.eval", phase="eval"):
            for j in range(max(1, int(tokens.shape[0]) // int(batch_size))):
                rows = tokens[j * batch_size : (j + 1) * batch_size]
                if rows.shape[0] < batch_size:
                    break
                t = self._put(rows[proc * b_local : (proc + 1) * b_local])
                _mem.tag("eval", t)
                losses.append(self._eval_step(self.state, t)["loss"])
            if not losses:
                return None
            return float(jnp.mean(jnp.stack(losses)))

    @staticmethod
    def _ppl(loss: float) -> float:
        from tpuflow.models.transformer import perplexity

        return perplexity(loss)

    def fit(
        self,
        train_tokens: "np.ndarray | TokenDataset",
        batch_size: int,
        epochs: Optional[int] = None,
        val_tokens: Optional[np.ndarray] = None,
        checkpoint_dir: Optional[str] = None,
        run=None,
        initial_epoch: Optional[int] = None,
        on_epoch: Optional[Callable[[int, Dict[str, float]], None]] = None,
        elastic=None,
    ) -> Dict[str, float]:
        """Train on ``(N, seq_len)`` int32 token rows — either in-memory
        (a numpy array) or streamed from disk (a
        :class:`tpuflow.data.tokens.TokenDataset`, the beyond-host-RAM
        path: O(shuffle buffer) RSS regardless of corpus size). Returns
        the final epoch's metrics. Deterministic per-epoch shuffle
        (seeded by config.seed + epoch, so resume replays the right
        order; the TokenDataset seeds its stream the same way).

        ``initial_epoch`` defaults to the epoch recorded by the last
        :meth:`maybe_resume` — consumed ONCE, so a later fit() on the
        same trainer continues fresh instead of replaying old epochs
        (pass it explicitly for full control, ≙ Trainer.fit). If no
        epochs remain (a restart landed on the final checkpoint), the
        restored model is evaluated instead so the returned metrics
        always carry ``loss``.

        ``elastic`` is an optional
        :class:`tpuflow.train.recovery.ElasticController`: polled at
        superstep block boundaries (every step for K=1 — each boundary
        is clean), a world change re-shards the state under a rebuilt
        mesh and rescales the LR per Goyal et al. (single-controller
        in-process; multi-process runs persist a sharded checkpoint
        and exit for the relauncher). ``cfg.recovery`` arms the
        watchdog-trip → rollback-to-last-good-checkpoint ladder
        (tpuflow.train.recovery.RecoveryPolicy)."""
        cfg = self.cfg
        epochs = epochs if epochs is not None else cfg.epochs
        K = max(1, int(getattr(cfg, "superstep", 1)))
        if getattr(cfg, "superstep", 1) < 1:
            raise ValueError(f"superstep must be >= 1, got {cfg.superstep}")
        if getattr(cfg, "compilation_cache_dir", None):
            from tpuflow.core.hw import enable_compilation_cache

            enable_compilation_cache(cfg.compilation_cache_dir)
        if self.state is None:
            self.init_state()
        if self._train_step is None:
            self._make_steps()
        start = (
            initial_epoch if initial_epoch is not None
            else self._initial_epoch
        )
        self._initial_epoch = 0  # consume-once (see docstring)
        b_local, proc = self._local_slice(batch_size)
        ds = train_tokens if isinstance(train_tokens, TokenDataset) else None
        if ds is not None:
            want_cur, want_count = self._expected_shard()
            if ds.batch_rows != b_local or ds.shard_count != want_count:
                raise ValueError(
                    f"TokenDataset(batch_rows={ds.batch_rows}, "
                    f"shard_count={ds.shard_count}) does not match this "
                    f"topology: need batch_rows={b_local} and "
                    f"shard_count={want_count}"
                )
            if ds.cur_shard != want_cur:
                # an explicit shard=(0, n) copied onto every host would
                # pass the count check yet stream IDENTICAL rows on all
                # ranks — duplicated batches, most of the corpus unseen
                raise ValueError(
                    f"TokenDataset.cur_shard={ds.cur_shard} but this "
                    f"trainer expects shard=({want_cur}, {want_count}); "
                    "use shard=None (auto) for data-sharded feeds"
                )
            n = ds.total_rows
            steps_per_epoch = ds.steps_per_epoch()
            seq_len = ds.seq_len
        else:
            n = int(train_tokens.shape[0])
            if n < batch_size:
                # fail loudly up front: a short row set would floor
                # steps_per_epoch to an undersized batch, and in
                # multi-process DP the per-process slices can be unequal
                # or empty — a confusing mid-fit _put error
                raise ValueError(
                    f"train_tokens has {n} rows < batch_size={batch_size}; "
                    "provide at least one full global batch"
                )
            steps_per_epoch = max(1, n // int(batch_size))
            seq_len = int(train_tokens.shape[1])
        self.lr_controller = LRController(
            cfg.learning_rate,
            world_size=self.world,
            scale_by_world_size=cfg.scale_lr_by_world_size,
            warmup_epochs=cfg.warmup_epochs,
            steps_per_epoch=steps_per_epoch,
            decay=cfg.lr_decay,
            total_steps=epochs * steps_per_epoch,
            min_lr=cfg.min_lr,
        )
        if start >= epochs:
            # nothing left to train — report eval metrics of the
            # restored state rather than an empty dict
            metrics = self.evaluate(train_tokens, batch_size)
            if val_tokens is not None:
                vl = self._eval_mean_loss(val_tokens, batch_size)
                if vl is not None:
                    metrics["val_loss"] = vl
                    metrics["val_ppl"] = self._ppl(vl)
            return metrics
        metrics: Dict[str, float] = {}
        # exact mid-epoch resume (maybe_resume with steps_per_epoch)
        skip_steps = int(getattr(self, "_resume_skip_steps", 0) or 0)
        self._resume_skip_steps = 0
        # preemption-safe mode: SIGTERM sets a flag; the step loop
        # finishes the current step, writes a step checkpoint, stops
        # cleanly (same contract as the image Trainer). Gates and
        # handler install/restore are shared in train/preempt.py.
        from tpuflow.train.preempt import (should_stop,
                                           sigterm_preempt_flag)

        use_preempt = bool(
            getattr(cfg, "checkpoint_on_preempt", False) and checkpoint_dir
        )
        preempt_mp = jax.process_count() > 1
        sync_every = int(getattr(cfg, "preempt_sync_every", 16))
        if skip_steps:
            # the stashed mid-epoch position is only meaningful for the
            # EXACT topology maybe_resume was told about — a different
            # batch size / dataset (different steps_per_epoch) or an
            # explicit initial_epoch override would apply the skip to
            # the wrong stream position and silently break exact resume
            if skip_steps >= steps_per_epoch:
                raise ValueError(
                    f"resume position (+{skip_steps} steps) does not fit "
                    f"steps_per_epoch={steps_per_epoch}: maybe_resume was "
                    "given a different steps_per_epoch — call fit with "
                    "the same batch size and data"
                )
            resumed_epoch = getattr(self, "_resume_epoch", None)
            if resumed_epoch is not None and start != resumed_epoch:
                raise ValueError(
                    f"initial_epoch={start} overrides the resumed "
                    f"mid-epoch position (epoch {resumed_epoch} "
                    f"+{skip_steps} steps) — drop initial_epoch or "
                    "re-run maybe_resume"
                )
        global_step = start * steps_per_epoch + skip_steps
        # shapes are fixed within one fit but not across fits — stale
        # FLOPs (or a stale AOT executable) from a previous fit's
        # shapes would corrupt MFU / fail on call
        self._flops_per_step = None
        self._step_exec = None
        # superstep AOT executables, one per block size (the full-K
        # program plus at most one remainder-tail size per fit)
        self._sstep_execs = {}
        # metrics/health plane (ISSUE 5): Prometheus exporter
        # (cfg.metrics_port) + watchdogs (cfg.watchdog /
        # cfg.stall_timeout_s / cfg.flight_dir). None when disarmed —
        # the loop then pays one `is not None` check per step.
        from tpuflow.obs.health import monitor_from_config

        self.health = monitor_from_config(cfg)
        # fault-tolerance plane (ISSUE 10): the recovery ladder turns
        # watchdog trips into rollback-and-replay instead of the
        # halt-and-dump below; the fault-injection hooks in the step
        # loops cost one dict-truthiness check when disarmed.
        from tpuflow.testing import faults
        from tpuflow.train.recovery import (policy_from_config,
                                            record_recovery)

        policy = policy_from_config(cfg)
        if policy is not None and self.health is None:
            raise ValueError(
                "cfg.recovery has no trip source: arm watchdog=True "
                "(or stall_timeout_s) so there is something to "
                "recover from"
            )
        self._recovery_policy = policy  # introspection (tests, bench)
        self._recovery_skip: set = set()  # steps whose batch replay drops
        rollback_anchor = global_step  # ladder progress accounting
        from tpuflow.ckpt.checkpoint import join_async_writes

        from tpuflow.obs.health import closing as _closing_monitor

        preempted = False
        sharded = bool(getattr(cfg, "sharded_checkpoint", False))
        keep_last = getattr(cfg, "keep_last_checkpoints", None)
        # epoch cursor is a while loop: a recovery rollback or an
        # elastic resize re-enters an earlier/same epoch at an exact
        # step position (resume_epoch/resume_skip generalize the
        # mid-epoch preemption-resume fast-forward)
        epoch = start
        resume_epoch, resume_skip = start, skip_steps
        with sigterm_preempt_flag(use_preempt) as preempt, \
                join_async_writes(lambda: [self._async_ckpt]), \
                _closing_monitor(self.health):
            while epoch < epochs:
                # explicit begin/end (idempotent) — the body exits
                # through break paths too
                ep_span = trace.begin("train.epoch", epoch=epoch)
                if self.health is not None:
                    # stepping resumes: the stall clock re-anchors
                    self.health.resume()
                first_i = resume_skip if epoch == resume_epoch else 0
                if ds is not None:
                    batch_iter = ds.iter_epoch(epoch)
                    for _ in range(first_i):
                        next(batch_iter)  # fast-forward to the resume point
                else:
                    order = np.random.default_rng(cfg.seed + epoch).permutation(n)
                losses = []
                t_epoch = None
                timed_steps = 0

                def _host_rows(i):
                    """Local token rows for global step index ``i`` of
                    this epoch — the SAME selection the per-step loop
                    makes (stream order / seeded shuffle slice)."""
                    if ds is not None:
                        # shard-disjoint stream: this process's slice comes
                        # from its own round-robin rows (≙ cur_shard=rank)
                        return next(batch_iter)
                    # the shuffle order is seed-deterministic, so every
                    # process slices the SAME global batch and takes its
                    # own contiguous rows (≙ cur_shard=rank, P1/03:332-337)
                    rows = order[i * batch_size : (i + 1) * batch_size]
                    rows = rows[proc * b_local : (proc + 1) * b_local]
                    return train_tokens[rows]

                resize = None  # (new_world, step_index) from elastic
                if K > 1:
                    # superstep mode: one fused K-step scan dispatch per
                    # block (device-resident (k,) loss blocks; the only
                    # per-epoch host sync is the timing anchor after the
                    # first block), double-buffered staging, and blocks
                    # chunked so multi-process preempt-sync agreement
                    # points always land on block edges
                    (preempted, global_step, lr, t_epoch, timed_steps,
                     resize) = (
                        self._run_superstep_epoch(
                            K, first_i, steps_per_epoch, global_step,
                            losses, _host_rows, preempt, use_preempt,
                            sync_every, preempt_mp, policy, elastic,
                            rollback_anchor,
                        )
                    )
                else:
                    for i in range(first_i, steps_per_epoch):
                        if use_preempt and should_stop(
                                preempt, global_step, sync_every,
                                preempt_mp):
                            preempted = True
                            break
                        if (self.health is not None
                                and self.health.tripped):
                            break
                        # every K=1 step edge is a clean resize point
                        # (the degenerate superstep block boundary)
                        faults.fire("elastic.boundary",
                                    step=global_step)
                        if elastic is not None:
                            nw = elastic.check(self.world)
                            if nw is not None:
                                resize = (nw, i)
                                break
                        with trace.span("train.data_wait",
                                        phase="data_wait"):
                            local_rows = _host_rows(i)
                        if global_step in self._recovery_skip:
                            # skip-batch escalation: the poisoned
                            # step's batch is consumed from the stream
                            # but never trained on — the only forward
                            # path past a deterministically toxic batch
                            global_step += 1
                            continue
                        faults.fire("train.step", step=global_step)
                        with trace.span("train.device_put",
                                        phase="data_wait"):
                            toks = self._put(local_rows)
                            _mem.tag("data_staging", toks)
                        lr = self.lr_controller.lr_for_step(global_step)
                        if policy is not None:
                            lr *= policy.lr_scale  # escalation drop
                        lr_arr = jnp.asarray(lr, jnp.float32)
                        if self._step_exec is None:
                            # ONE compile per fit: the AOT executable both
                            # runs every step (jax's AOT path does not share
                            # the jit dispatch cache — compiling separately
                            # for cost analysis would double the compile)
                            # and feeds the executable registry + the FLOPs
                            # for the throughput/MFU metrics (N11). MFU
                            # keeps the PER-DEVICE share (mean across the
                            # cost-analysis device shares).
                            with trace.span("train.compile",
                                            phase="compile"):
                                self._step_exec = (
                                    self._train_step.aot_compile(
                                        self.state, toks, lr_arr
                                    )
                                )
                            ca = self._aot_cost(self._train_step,
                                                self._step_exec)
                            self._flops_per_step = ca.get(
                                "flops", 0.0
                            ) / max(1, ca.get("per_device", 1))
                        with trace.span("train.dispatch",
                                        phase="dispatch"):
                            self.state, m = self._step_exec(
                                self.state, toks, lr_arr
                            )
                        m = faults.mutate_metrics("train.metrics", m,
                                                  step=global_step)
                        losses.append(m["loss"])
                        if self.health is not None:
                            # device-resident handoff — the monitor's
                            # worker thread pays the fetch, this
                            # thread keeps dispatching
                            self.health.watch_device(global_step, m)
                        global_step += 1
                        if i == first_i:
                            # sync, then time the REMAINING steps: the first
                            # executed step carries trace+compile, which must
                            # not pollute the throughput metrics
                            with trace.span("train.sync",
                                            phase="device"):
                                float(m["loss"])
                            t_epoch = time.time()
                            timed_steps = steps_per_epoch - first_i - 1
                if resize is not None:
                    # elastic data-parallel resize (ISSUE 10): a
                    # replica was lost/joined and the controller agreed
                    # on a new world at this block boundary
                    new_world, at_i = resize
                    old_world = self.world
                    if (jax.process_count() == 1
                            and batch_size % new_world):
                        # an incompatible target world must not tear
                        # down a healthy run — refuse and train on;
                        # the controller suppresses the refused target
                        # until its oracle changes its answer (a
                        # zero-interval controller would otherwise
                        # re-ask at every boundary and starve the fit)
                        elastic.refuse(new_world)
                        if is_primary():
                            print(
                                f"elastic resize to world={new_world} "
                                f"refused: global batch {batch_size} "
                                "not divisible by the new data axis"
                            )
                        resume_epoch, resume_skip = epoch, at_i
                        trace.end(ep_span, resize_refused=True)
                        continue
                    if jax.process_count() > 1:
                        # multi-process: the gang itself must change, so
                        # persist a SHARDED checkpoint (restore under
                        # the new process count re-slices it) and exit
                        # for the relauncher
                        if checkpoint_dir:
                            from tpuflow.ckpt.sharded import (
                                save_sharded_checkpoint)

                            with trace.span("train.checkpoint",
                                            phase="checkpoint"):
                                save_sharded_checkpoint(
                                    checkpoint_dir, self.state,
                                    global_step)
                        metrics = dict(metrics)
                        metrics["elastic_exit_at_step"] = float(
                            global_step)
                        # fit RETURNS (a library cannot sys.exit);
                        # the driver script must see this key and exit
                        # nonzero / re-exec so the cluster manager
                        # relaunches with the new process count — the
                        # --local relauncher cannot (it replays the
                        # SAME world), which is why this is the
                        # multi-process path only
                        metrics["elastic_desired_world"] = float(
                            new_world)
                        if is_primary():
                            print(f"elastic resize {old_world}->"
                                  f"{new_world} at step {global_step}: "
                                  "sharded checkpoint saved; caller "
                                  "must relaunch the gang at the new "
                                  "world (metrics carry "
                                  "elastic_desired_world)")
                        trace.end(ep_span, elastic_exit=True)
                        break
                    # single-controller: rebuild the mesh in-process,
                    # re-shard the state under it, rescale the LR per
                    # Goyal et al. (the LRController's world scaling)
                    self._resize_world(new_world)
                    self.lr_controller = LRController(
                        cfg.learning_rate,
                        world_size=self.world,
                        scale_by_world_size=cfg.scale_lr_by_world_size,
                        warmup_epochs=cfg.warmup_epochs,
                        steps_per_epoch=steps_per_epoch,
                        decay=cfg.lr_decay,
                        total_steps=epochs * steps_per_epoch,
                        min_lr=cfg.min_lr,
                    )
                    b_local, proc = self._local_slice(batch_size)
                    elastic.note_resize(old_world, new_world,
                                        global_step)
                    if is_primary():
                        print(f"elastic resize {old_world}->{new_world} "
                              f"at step {global_step} (lr x"
                              f"{new_world / old_world:g} via world "
                              "scaling)")
                    resume_epoch, resume_skip = epoch, at_i
                    trace.end(ep_span, resized=True)
                    continue
                if preempted:
                    from tpuflow.ckpt.checkpoint import save_step_checkpoint

                    with trace.span("train.checkpoint",
                                    phase="checkpoint"):
                        if sharded:
                            from tpuflow.ckpt.sharded import (
                                save_sharded_checkpoint)

                            spath = save_sharded_checkpoint(
                                checkpoint_dir, self.state, global_step
                            )
                        else:
                            spath = save_step_checkpoint(
                                checkpoint_dir, self.state, global_step
                            )
                    metrics["preempted_at_step"] = float(global_step)
                    if is_primary():
                        print(f"preempted at step {global_step}; saved {spath}")
                    trace.end(ep_span, preempted=True)
                    break
                if self.health is not None:
                    # the step loop is over: pause the stall watch so
                    # an epoch-end eval/checkpoint longer than the
                    # timeout never reads as a stall, then settle the
                    # async guard so a trip in this epoch's tail stops
                    # the run NOW, not one epoch of chip-hours later
                    self.health.pause()
                    self.health.drain()
                    if self.health.tripped:
                        trips = self.health.trips()
                        tstep = int(next(
                            (t["step"] for t in trips
                             if "step" in t), global_step
                        ))
                        reason = (trips[0].get("reason",
                                               "watchdog trip")
                                  if trips else "watchdog trip")
                        act = (policy.on_trip(tstep, reason=reason)
                               if policy is not None else None)
                        if act is not None and act.kind == "rollback":
                            # auto-recovery (ISSUE 10): rollback to the
                            # last GOOD checkpoint and replay, instead
                            # of halt-and-dump. Corrupt/truncated files
                            # are skipped by discovery; nothing on disk
                            # yet ⇒ restart from the seed init.
                            if act.backoff_s > 0:
                                time.sleep(act.backoff_s)
                            from tpuflow.ckpt.checkpoint import (
                                latest_resume_point)

                            found = (latest_resume_point(
                                checkpoint_dir, steps_per_epoch)
                                if checkpoint_dir else None)
                            if found is not None:
                                rpath, r_epoch, r_skip = found
                                with trace.span("train.rollback",
                                                phase="checkpoint"):
                                    self.state = restore_into_state(
                                        rpath, self.state)
                            else:
                                rpath, r_epoch, r_skip = None, 0, 0
                                self.init_state()
                            self._tag_state()
                            rollback_to = (r_epoch * steps_per_epoch
                                           + r_skip)
                            if int(self.state.step) != rollback_to:
                                # weights-only checkpoint (the restore
                                # branch that keeps step/opt_state):
                                # the POISONED optimizer moments would
                                # re-NaN every replay — re-init the
                                # optimizer fresh at the rollback
                                # point. Fresh moments follow the
                                # params' layout, not a zero1/fsdp
                                # spec, so the AOT executables must
                                # re-derive from the actual state
                                self.state = self.state.replace(
                                    step=rollback_to,
                                    opt_state=self.tx.init(
                                        self.state.params),
                                )
                                self._step_exec = None
                                self._sstep_execs = {}
                            if act.skip_step is not None:
                                self._recovery_skip.add(act.skip_step)
                            record_recovery(
                                policy, rollback_from=global_step,
                                rollback_to=rollback_to)
                            # consume the trip: the monitor re-arms
                            # (fresh spike EWMA) but the process
                            # watchdog keeps the latched history for
                            # flight manifests / post-mortems
                            self.health.acknowledge()
                            if is_primary():
                                print(
                                    f"watchdog tripped ({reason}); "
                                    f"rollback #{act.retry} to step "
                                    f"{rollback_to} "
                                    + (f"[{rpath}]" if rpath
                                       else "[re-init]")
                                    + (f", lr x{act.lr_scale:g}"
                                       if act.lr_scale != 1.0 else "")
                                    + (f", skipping batch of step "
                                       f"{act.skip_step}"
                                       if act.skip_step is not None
                                       else "")
                                )
                            global_step = rollback_to
                            epoch = r_epoch
                            resume_epoch, resume_skip = r_epoch, r_skip
                            rollback_anchor = rollback_to
                            trace.end(ep_span, rollback=True)
                            continue
                        metrics = dict(metrics)
                        metrics["watchdog_tripped_at"] = float(tstep)
                        if is_primary():
                            why = (act.reason if act is not None
                                   else reason)
                            print(f"watchdog tripped: {why}; "
                                  f"stopping at step {global_step}")
                        trace.end(ep_span, watchdog_tripped=True)
                        break
                with trace.span("train.metrics_fetch", phase="device"):
                    epoch_loss = float(jnp.mean(jnp.concatenate(
                        [jnp.atleast_1d(l) for l in losses]
                    )))
                # the scalar fetch above syncs, so the wall time is real
                epoch_s = time.time() - t_epoch if t_epoch is not None else 0.0
                metrics = {"loss": epoch_loss, "lr": float(lr)}
                # re-tag the (donation-replaced) state at the epoch
                # boundary so the ledger's params/opt_state stay honest
                self._tag_state()
                if timed_steps > 0 and epoch_s > 0:
                    step_s = epoch_s / timed_steps
                    metrics["tokens_per_sec"] = batch_size * seq_len / step_s
                    if self._flops_per_step:
                        from tpuflow.core.hw import is_tpu_backend
                        from tpuflow.obs.mfu import mfu as _mfu

                        # n_chips=1: on TPU, cost analysis reports the
                        # PER-DEVICE share of the SPMD-partitioned step. On
                        # other backends (the CPU host-device meshes of the
                        # test suite) it can report WHOLE-PROGRAM flops —
                        # divide by mesh size there so the logged mfu is not
                        # inflated by the device count (ADVICE r2).
                        fl = self._flops_per_step
                        if not is_tpu_backend():
                            fl /= max(1, self.mesh.size)
                        metrics["mfu"] = _mfu(
                            fl, step_s, n_chips=1,
                            device=self.mesh.devices.flat[0],
                        )
                # first-class plane gauges (ISSUE 5 satellite): the
                # exporter/ring see live MFU + FLOPs without a run
                # handle — bench computes the same numbers, this makes
                # them scrape-able during any fit
                from tpuflow.obs.gauges import set_gauge

                set_gauge("train.loss", epoch_loss)
                set_gauge("train.epoch", float(epoch))
                if self._flops_per_step:
                    set_gauge("train.flops_per_step",
                              float(self._flops_per_step))
                for gk in ("tokens_per_sec", "mfu"):
                    if gk in metrics:
                        set_gauge(f"train.{gk}", float(metrics[gk]))
                if val_tokens is not None:
                    vl = self._eval_mean_loss(val_tokens, batch_size)
                    if vl is not None:
                        metrics["val_loss"] = vl
                        metrics["val_ppl"] = self._ppl(vl)
                # rank-0-only tracking side effects (≙ P1/03:360-361);
                # ``run`` is a tpuflow.track Run handle, same idiom as
                # TrackingCallback on the image Trainer
                if run is not None and is_primary():
                    for k, v in metrics.items():
                        run.log_metric(k, float(v), step=epoch)
                if checkpoint_dir:
                    with trace.span("train.checkpoint",
                                    phase="checkpoint"):
                        wrote = None
                        if sharded:
                            # sharded epoch-boundary checkpoint: step
                            # namespace (manifests speak global steps);
                            # resume via maybe_resume(steps_per_epoch=)
                            from tpuflow.ckpt.sharded import (
                                save_sharded_checkpoint)

                            wrote = save_sharded_checkpoint(
                                checkpoint_dir, self.state,
                                (epoch + 1) * steps_per_epoch,
                            )
                        elif getattr(cfg, "async_checkpoint", False):
                            if self._async_ckpt is None:
                                from tpuflow.ckpt import AsyncCheckpointer

                                self._async_ckpt = AsyncCheckpointer()
                            self._async_ckpt.save(
                                checkpoint_dir, self.state, epoch + 1
                            )
                        else:
                            wrote = save_checkpoint(
                                checkpoint_dir, self.state, epoch + 1
                            )
                    if keep_last:
                        from tpuflow.ckpt.checkpoint import gc_checkpoints

                        # just_wrote: the file this save produced needs
                        # no re-read for the newest-valid rail (async
                        # saves pass None — the write may be in flight)
                        gc_checkpoints(checkpoint_dir, keep_last,
                                       just_wrote=wrote)
                if policy is not None:
                    # clean steps since the last rollback: past the
                    # reset threshold the escalation ladder clears
                    policy.note_progress(global_step - rollback_anchor)
                if on_epoch is not None:
                    on_epoch(epoch, metrics)
                trace.end(ep_span)
                epoch += 1
        # the stall thread stopped with the closing() cm above (even on
        # exception paths); trip state stays readable on self.health
        return metrics

    def _run_superstep_epoch(self, K, first_i, steps_per_epoch,
                             global_step, losses, host_rows, preempt,
                             use_preempt, sync_every, preempt_mp,
                             policy=None, elastic=None,
                             rollback_anchor=0):
        """One epoch of superstep execution (cfg.superstep > 1): fused
        K-step scan dispatches over stacked token blocks.

        - ``host_rows(i)`` supplies the SAME local rows the per-step
          loop would feed at step index ``i`` — parity by construction;
        - staging is double-buffered: block i+1 is assembled and
          ``device_put`` while the device still executes block i (the
          dispatch below is async; nothing blocks until the timing
          anchor after the first block);
        - the per-step losses stay device-resident as (k,) blocks in
          ``losses`` (fetched once at epoch end);
        - blocks are AOT-compiled once per distinct size (the full-K
          program + at most one remainder tail) and chunked so
          multi-process preemption agreement points land on block
          edges — the collective schedule across processes is identical
          to the K=1 loop's;
        - block edges are the clean boundaries of the fault-tolerance
          plane (ISSUE 10): the elastic controller is polled there, and
          a recovery-skip step (escalation level 3) splits its block —
          the poisoned batch is consumed from the stream but never
          dispatched (the split may add one compile size per distinct
          sub-run length; only reachable after ``skip_batch_after``
          consecutive trips).

        Returns ``(preempted, global_step, lr, t_epoch, timed_steps,
        resize)`` where ``resize`` is ``(new_world, epoch_step_index)``
        or None.
        """
        import collections

        from tpuflow.testing import faults
        from tpuflow.train.preempt import should_stop, superstep_sizes

        sizes = superstep_sizes(
            steps_per_epoch - first_i, K, global_step,
            sync_every if (use_preempt and preempt_mp) else 0,
        )
        if self._recovery_skip:
            # split each planned block at recovery-skip steps (sync
            # edges of the original plan are preserved — splitting only
            # subdivides within a block)
            plan = []
            consumed = 0
            for sz in sizes:
                run = 0
                for _j in range(sz):
                    if (global_step + consumed) in self._recovery_skip:
                        if run:
                            plan.append(("train", run))
                            run = 0
                        plan.append(("skip", 1))
                    else:
                        run += 1
                    consumed += 1
                if run:
                    plan.append(("train", run))
        else:
            plan = [("train", sz) for sz in sizes]
        depth = 2  # classic double buffer: assemble i+1 while i runs

        def blocks():
            buf = collections.deque()
            i = first_i
            for kind, want in plan:
                if kind == "skip":
                    # consume the poisoned step's rows, stage nothing
                    host_rows(i)
                    i += 1
                    buf.append(("skip", 1, None))
                else:
                    with trace.span("train.data_wait",
                                    phase="data_wait", k=want):
                        rows = [host_rows(i + j) for j in range(want)]
                    i += want
                    with trace.span("train.device_put",
                                    phase="data_wait", k=want):
                        blk = self._put_block(rows)
                        _mem.tag("data_staging", blk)
                        buf.append(("train", want, blk))
                if len(buf) >= depth:
                    yield buf.popleft()
            while buf:
                yield buf.popleft()

        blk_iter = blocks()
        preempted = False
        resize = None
        t_epoch = None
        timed_steps = 0
        i_epoch = first_i
        lr = self.lr_controller.lr_for_step(global_step)
        for _ in plan:
            if use_preempt and should_stop(
                    preempt, global_step, sync_every, preempt_mp):
                preempted = True
                break
            if self.health is not None and self.health.tripped:
                break
            faults.fire("elastic.boundary", step=global_step)
            if elastic is not None:
                nw = elastic.check(self.world)
                if nw is not None:
                    resize = (nw, i_epoch)
                    break
            kind, k, toks = next(blk_iter)
            if kind == "skip":
                # skip-batch escalation: stream consumed, step counted,
                # nothing trained
                global_step += 1
                i_epoch += 1
                continue
            for j in range(k):
                faults.fire("train.step", step=global_step + j)
            lr_list = [
                self.lr_controller.lr_for_step(global_step + j)
                for j in range(k)
            ]
            if policy is not None and policy.lr_scale != 1.0:
                lr_list = [v * policy.lr_scale for v in lr_list]
            lr = lr_list[-1]
            lrs_arr = jnp.asarray(lr_list, jnp.float32)
            ex = self._sstep_execs.get(k)
            if ex is None:
                if self.health is not None:
                    # a mid-epoch compile (the remainder-tail block
                    # size) may legitimately exceed stall_timeout_s;
                    # it is not step silence
                    self.health.pause()
                with trace.span("train.compile", phase="compile", k=k):
                    ex = self._superstep.aot_compile(
                        self.state, toks, lrs_arr
                    )
                if self.health is not None:
                    self.health.resume()
                self._sstep_execs[k] = ex
                if self._flops_per_step is None:
                    # XLA cost analysis counts a lax.scan body ONCE, so
                    # the K-step program reports ~one step's FLOPs —
                    # exactly the per-step number the MFU metrics want
                    # (same convention as the grad-accum scan, bench.py)
                    ca = self._aot_cost(self._superstep, ex)
                    self._flops_per_step = ca.get(
                        "flops", 0.0
                    ) / max(1, ca.get("per_device", 1))
            with trace.span("train.superstep", phase="dispatch", k=k):
                self.state, m = ex(self.state, toks, lrs_arr)
            m = faults.mutate_metrics("train.metrics", m,
                                      step=global_step + k - 1, k=k)
            losses.append(m["loss"])
            if self.health is not None:
                # whole (k,)-stacked block, still device-resident; the
                # guard attributes a bad entry to its exact step
                self.health.watch_device(global_step + k - 1, m)
            global_step += k
            i_epoch += k
            if t_epoch is None:
                # sync after the FIRST block only: compile stays out of
                # the timed window, and this is the epoch's single
                # mid-flight host fetch
                with trace.span("train.sync", phase="device"):
                    float(np.asarray(m["loss"])[-1])
                t_epoch = time.time()
                timed_steps = steps_per_epoch - first_i - k
        return (preempted, global_step, lr, t_epoch, timed_steps,
                resize)

    # ---- evaluation ------------------------------------------------------

    def evaluate(
        self, tokens: "np.ndarray | TokenDataset", batch_size: int
    ) -> Dict[str, float]:
        if self.state is None:
            self.init_state()
        if self._eval_step is None:
            self._make_steps()
        loss = self._eval_mean_loss(tokens, batch_size)
        if loss is None:
            n = (
                tokens.total_rows if isinstance(tokens, TokenDataset)
                else int(tokens.shape[0])
            )
            raise ValueError(
                f"evaluate needs at least one full batch: got "
                f"{n} rows < batch_size={batch_size}"
            )
        return {"loss": loss, "ppl": self._ppl(loss)}
