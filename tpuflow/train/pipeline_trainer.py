"""Trainer-level pipeline parallelism for the decoder LM.

Promotes the raw GPipe demonstration of examples/10_pipeline_lm.py to a
full trainer (VERDICT r2 #4): optimizer-by-name, LR control,
checkpoint/resume, tracking and the fit/evaluate surface all come from
:class:`tpuflow.train.lm.LMTrainer`; this subclass swaps the step
construction for a pipelined one over a ``pipe`` mesh axis.

Topology: the decoder stack is cut into ``n_stages = mesh['pipe']``
equal stages (``depth % n_stages == 0``), each device holding its
stage's blocks as a slice of STACKED per-stage parameter trees
(tpuflow.parallel.pipeline.stack_stage_params, sharded ``P('pipe')``).
Embedding runs replicated before the pipeline; final norm + LM head
after it (GPipe) or inside the last stage (1F1B, which needs the
per-microbatch loss to seed each backward).

Schedules:

- ``schedule='gpipe'``: the forward is the ``lax.scan`` fill/steady/
  drain schedule of tpuflow.parallel.pipeline.pipeline; backward falls
  out of autodiff (activation memory O(n_micro)).
- ``schedule='1f1b'``: tpuflow.parallel.pipeline.pipeline_1f1b — one
  forward and one backward op per tick, residuals in a circular
  buffer, activation memory O(n_stages) (PipeDream-flush). Same math;
  better memory and the same bubble.
- ``schedule='interleaved'``: Megatron-style virtual-stage 1F1B
  (tpuflow.parallel.interleave + pipeline_interleaved) — each device
  holds ``virtual_stages`` round-robin model chunks and the schedule
  runs one CHUNK op per slot, shrinking the flush bubble by ~v× for
  ~v× the resident activations. Same math again (grads accumulate
  over all microbatches before the optimizer step).

The reference has no pipeline story at all (SURVEY.md §2c — Horovod DP
is its only training parallelism); this is part of the beyond-reference
scale surface, alongside ring-attention SP and GSPMD TP/ZeRO/EP.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from tpuflow.core.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from tpuflow.core.config import TrainConfig
from tpuflow.obs.executables import registered_jit as _registered_jit
from tpuflow.models.transformer import (
    DecoderBlock,
    RMSNorm,
    TransformerLM,
    lm_head_dot,
    next_token_loss,
)
from tpuflow.parallel.mesh import build_nd_mesh
from tpuflow.parallel.interleave import build_interleaved_schedule
from tpuflow.parallel.pipeline import (
    PIPE_AXIS,
    from_last_stage,
    pipeline,
    pipeline_1f1b,
    pipeline_interleaved,
    pipeline_interleaved_fwd,
    split_microbatches,
    stack_stage_params,
)
from tpuflow.train.lm import LMTrainer
from tpuflow.train.optimizers import set_learning_rate
from tpuflow.train.state import TrainState


class PipelineTrainer(LMTrainer):
    """Pipeline-parallel LM trainer (GPipe, 1F1B or Megatron-interleaved
    microbatch schedule).

    ``mesh`` must carry a ``pipe`` axis (default: a 1-D pipe mesh over
    all local devices) and may additionally carry a ``data`` axis for
    DP x PP: microbatch ROWS are sharded over ``data`` while stages
    are laid over ``pipe`` — each data replica runs the full microbatch
    schedule on its slice and gradients are mean-reduced across
    replicas (GPipe: by shard_map's autodiff transpose; 1F1B family: an
    explicit pmean after the schedule). ``batch_size`` in :meth:`fit`
    is global and must divide by ``n_microbatches`` x the data-axis
    size.

    A ``model`` axis composes tensor parallelism with the pipeline
    (DP x TP x PP on one mesh): the schedule's shard_map is manual
    over pipe/data only, ``model`` stays a GSPMD auto axis, so the
    blocks' existing ``with_partitioning`` annotations shard each
    stage's kernels and XLA inserts the TP collectives inside every
    pipeline tick. All three schedules support it.

    ``schedule='interleaved'`` additionally takes ``virtual_stages=v``:
    each device holds ``v`` round-robin model chunks (``depth`` must
    divide by ``n_stages*v``, ``n_microbatches`` by ``n_stages``) and
    runs the Megatron virtual-stage schedule, shrinking the pipeline
    flush bubble by ~v× for ~v× the resident activations.
    """

    def __init__(
        self,
        model: TransformerLM,
        config: Optional[TrainConfig] = None,
        mesh=None,
        devices=None,
        n_microbatches: int = 8,
        schedule: str = "gpipe",
        virtual_stages: int = 1,
    ):
        if model.seq_axis is not None or model.n_experts > 0:
            raise ValueError(
                "PipelineTrainer pipelines the dense DP-free decoder "
                "stack; combine with seq_axis/MoE via LMTrainer instead"
            )
        if getattr(model, "tie_embeddings", False):
            raise ValueError(
                "tie_embeddings is not supported by PipelineTrainer "
                "yet: the tied head needs the embedding gradient "
                "accumulated from BOTH pipeline ends — use LMTrainer"
            )
        if schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(
                f"schedule must be 'gpipe', '1f1b' or 'interleaved', "
                f"got {schedule!r}"
            )
        if virtual_stages < 1:
            raise ValueError(f"virtual_stages must be >= 1, got "
                             f"{virtual_stages}")
        if virtual_stages > 1 and schedule != "interleaved":
            raise ValueError(
                "virtual_stages > 1 requires schedule='interleaved' "
                "(gpipe/1f1b run one contiguous stage per device)"
            )
        if mesh is None:
            n = len(devices) if devices is not None else len(jax.devices())
            mesh = build_nd_mesh({PIPE_AXIS: n}, devices=devices)
        if PIPE_AXIS not in mesh.axis_names:
            raise ValueError(
                f"mesh must carry a '{PIPE_AXIS}' axis, got "
                f"{mesh.axis_names}"
            )
        n_stages = mesh.shape[PIPE_AXIS]
        v = virtual_stages if schedule == "interleaved" else 1
        if model.depth % (n_stages * v):
            raise ValueError(
                f"depth {model.depth} must divide by n_stages x "
                f"virtual_stages = {n_stages}x{v}"
            )
        if n_microbatches < n_stages:
            raise ValueError(
                f"n_microbatches {n_microbatches} < n_stages {n_stages} "
                "leaves permanent bubbles; use at least n_stages "
                "(>= 4x to amortize, pipeline module docstring)"
            )
        if schedule == "interleaved" and n_microbatches % n_stages:
            raise ValueError(
                f"the interleaved schedule advances microbatches in "
                f"groups of n_stages; n_microbatches {n_microbatches} "
                f"must divide by {n_stages}"
            )
        super().__init__(model, config, mesh=mesh)
        if self.cfg.grad_accum_steps != 1:
            raise ValueError(
                "grad_accum_steps is not honored by PipelineTrainer: "
                "microbatching already splits the batch — raise "
                "n_microbatches instead"
            )
        if self.cfg.fused_loss:
            raise ValueError(
                "fused_loss is not honored by PipelineTrainer: the "
                "loss head runs inside the last pipeline stage's "
                "backward — per-microbatch logits are already "
                "chunk-sized there"
            )
        if self.cfg.packed_eos_id is not None:
            raise ValueError(
                "packed_eos_id (sequence packing) is not supported by "
                "PipelineTrainer yet — use LMTrainer for packed corpora"
            )
        self.n_stages = n_stages
        self.virtual_stages = v
        self.blocks_per_stage = model.depth // (n_stages * v)
        # model-slice index held by each row of the stacked param tree:
        # contiguous for gpipe/1f1b; DEVICE-MAJOR round-robin for
        # interleaved (device d's rows [d*v, (d+1)*v) hold model
        # slices d, d+n, d+2n, ...)
        if schedule == "interleaved":
            self._stage_order = [
                c * n_stages + d
                for d in range(n_stages)
                for c in range(v)
            ]
        else:
            self._stage_order = list(range(n_stages))
        self.n_microbatches = n_microbatches
        self.schedule = schedule
        # data-parallel degree (1 = pure PP); self.world from LMTrainer
        # already reads the data axis, so LR x world scaling Just Works
        self.dp = self.world
        # tensor parallelism COMPOSES with the pipeline via partial-
        # manual shard_map: the schedule is manual over pipe (+data)
        # while 'model' stays a GSPMD auto axis — the blocks' existing
        # with_partitioning annotations shard each stage's kernels and
        # XLA inserts the TP collectives inside every pipeline tick.
        # (self.tp itself comes from the LMTrainer base.)
        from tpuflow.parallel.mesh import MODEL_AXIS

        # manual axes for the schedule's shard_map; without a model
        # axis this equals all mesh axes = shard_map's default
        self._manual_axes = frozenset(mesh.axis_names) - {MODEL_AXIS}

    def _smap(self, body, in_specs, out_specs):
        """shard_map over the pipeline mesh — manual over pipe/data,
        leaving 'model' (when present) to GSPMD inside the body."""
        return shard_map(body, mesh=self.mesh, in_specs=in_specs,
                         out_specs=out_specs,
                         axis_names=self._manual_axes)

    # token rows shard over 'data' (if present) and replicate over
    # 'pipe' (stage 0 ingests them)
    def _token_spec(self):
        from tpuflow.parallel.mesh import DATA_AXIS

        if DATA_AXIS in self.mesh.axis_names:
            return P(DATA_AXIS)
        return P()

    # NOTE: no _local_slice/_expected_shard overrides needed — the base
    # LMTrainer derives the per-process feed from the token SHARDING's
    # addressable row ranges, which handles replicated (pure PP) and
    # partially-replicated (DP x PP across processes) feeds uniformly.

    # ---- state -----------------------------------------------------------

    def init_state(self, rng_seed: Optional[int] = None) -> TrainState:
        """Same init as the unpipelined LM (identical param values for
        parity), regrouped: ``params['outer']`` = embed / norm_final /
        lm_head (replicated), ``params['stages']`` = per-stage block
        trees stacked on a leading stage axis, sharded ``P('pipe')``."""
        from tpuflow.train.optimizers import get_optimizer

        seed = self.cfg.seed if rng_seed is None else rng_seed
        self.tx = get_optimizer(
            self.cfg.optimizer,
            self.cfg.learning_rate,
            grad_clip_norm=self.cfg.grad_clip_norm,
            **self.cfg.optimizer_kwargs,
        )
        toks0 = jnp.zeros((1, 8), jnp.int32)
        boxed = self.model.init({"params": jax.random.key(seed)}, toks0)
        raw = nn.unbox(boxed)["params"]
        outer = {k: v for k, v in raw.items() if not k.startswith("block")}
        per = self.blocks_per_stage
        stage_trees = [
            {
                f"b{j}": raw[f"block{s * per + j}"]
                for j in range(per)
            }
            for s in self._stage_order
        ]
        stacked = stack_stage_params(stage_trees)
        if self.tp > 1:
            # TP x PP: each leaf keeps its with_partitioning spec over
            # 'model', with the stacked stage axis prepended over 'pipe'
            spec = nn.get_partition_spec(boxed)["params"]
            s0 = self._stage_order[0]
            stage_spec = {
                f"b{j}": spec[f"block{s0 * per + j}"] for j in range(per)
            }
            is_p = lambda x: isinstance(x, P)  # noqa: E731
            outer_sh = jax.tree.map(
                lambda s: NamedSharding(self.mesh, s),
                {k: spec[k] for k in outer}, is_leaf=is_p,
            )
            stage_sh = jax.tree.map(
                lambda s: NamedSharding(self.mesh, P(PIPE_AXIS, *s)),
                stage_spec, is_leaf=is_p,
            )
            params = {
                "outer": jax.device_put(outer, outer_sh),
                "stages": jax.device_put(stacked, stage_sh),
            }
        else:
            params = {
                "outer": jax.device_put(
                    outer, NamedSharding(self.mesh, P())
                ),
                "stages": jax.device_put(
                    stacked, NamedSharding(self.mesh, P(PIPE_AXIS))
                ),
            }
        # commit EVERY leaf's placement explicitly: params carry their
        # pipe/model shardings above; scalars (step/rng/plateau) and
        # the optimizer's unsharded leaves (hyperparams, counts) get
        # the replicated sharding. Leaving them uncommitted happened to
        # work for fresh fits, but restore_into_state maps checkpoints
        # onto the TEMPLATE's shardings — an uncommitted scalar commits
        # the restored state to ONE device and the first step fails on
        # conflicting placements (same bug class fixed in LMTrainer
        # init_state, surfaced by the r05 preemption-resume tests).
        from tpuflow.parallel.mesh import put_replicated

        rep = NamedSharding(self.mesh, P())

        def _commit_rep(x):
            sh = getattr(x, "sharding", None)
            if isinstance(sh, NamedSharding):
                return x  # already mesh-committed (follows its param)
            # put_replicated, not raw device_put: multi-process meshes
            # are non-addressable, and typed PRNG keys need the
            # key-data round-trip either way
            return put_replicated(x, rep)

        self.state = TrainState(
            step=put_replicated(jnp.asarray(0, jnp.int32), rep),
            params=params,
            batch_stats={},
            opt_state=jax.tree.map(_commit_rep, self.tx.init(params)),
            rng=put_replicated(jax.random.key(seed), rep),
            plateau_factor=put_replicated(
                jnp.asarray(1.0, jnp.float32), rep
            ),
        )
        return self.state

    # ---- steps -----------------------------------------------------------

    def _stage_fn(self):
        m = self.model
        # mirror TransformerLM's remat_policy semantics: 'full' wraps
        # whole blocks, 'attn' checkpoints only the MLP sub-module
        cls = (
            nn.remat(DecoderBlock)
            if m.remat and m.remat_policy == "full" else DecoderBlock
        )
        # thread EVERY attention-shaping field the model carries — a
        # field silently defaulting here would make the pipelined model
        # compute different math than the same model under LMTrainer
        # (kv_heads/attn_window/attn_bh_block/rope_scaling were exactly
        # that gap)
        blk = cls(
            m.dim, m.heads, m.mlp_ratio, m.dtype,
            attn_impl=m.attn_impl, seq_axis=None,
            rope_theta=m.rope_theta,
            remat_mlp=m.remat and m.remat_policy == "attn",
            attn_window=m.attn_window,
            kv_heads=m.kv_heads,
            attn_bh_block=m.attn_bh_block,
            rope_scaling=m.rope_scaling,
            rope_scaling_kind=m.rope_scaling_kind,
        )

        def stage_fn(stage_params, x):
            for j in range(self.blocks_per_stage):
                x = blk.apply({"params": stage_params[f"b{j}"]}, x)
            return x

        return stage_fn

    def _head(self, norm_params, head_kernel, y):
        y = RMSNorm(self.model.dtype).apply({"params": norm_params}, y)
        return lm_head_dot(y, head_kernel)

    def _check_micro(self, tokens) -> None:
        mb = tokens.shape[0] // self.n_microbatches
        if tokens.shape[0] % self.n_microbatches or (
            self.dp > 1 and mb % self.dp
        ):
            raise ValueError(
                f"batch {tokens.shape[0]} must split into "
                f"{self.n_microbatches} microbatches of rows divisible "
                f"by the data-axis size {self.dp}"
            )

    def _make_steps(self) -> None:
        from tpuflow.obs import trace
        from tpuflow.parallel.mesh import DATA_AXIS

        # schedule construction is host work worth attributing: the
        # inherited LMTrainer fit loop carries the epoch/dispatch/
        # staging spans, this marks where the pipelined program itself
        # is assembled (jit compile lands in the first dispatch span)
        self._steps_span = trace.begin(
            "train.make_steps", schedule=self.schedule,
            stages=self.n_stages, virtual=self.virtual_stages,
        )
        model = self.model
        mesh = self.mesh
        mm = self.n_microbatches
        dp = self.dp
        has_data = DATA_AXIS in mesh.axis_names
        # microbatch buffers: (n_micro, rows, ...) — rows shard over
        # 'data' in DP x PP, stages always over 'pipe'
        micro_spec = P(None, DATA_AXIS) if has_data else P()
        stage_fn = self._stage_fn()
        if self.schedule == "interleaved":
            self._make_steps_interleaved(micro_spec, has_data, stage_fn)
            trace.end(self._steps_span)
            return
        run_fwd = pipeline(stage_fn, mm, PIPE_AXIS)

        def forward(params, tokens):
            self._check_micro(tokens)
            outer, stages = params["outer"], params["stages"]
            x = jnp.take(outer["embed"], tokens, axis=0).astype(model.dtype)
            micro = split_microbatches(x, mm)
            piped = self._smap(
                lambda sb, mi: from_last_stage(run_fwd(sb, mi), PIPE_AXIS),
                in_specs=(P(PIPE_AXIS), micro_spec),
                out_specs=micro_spec,
            )
            y = piped(stages, micro).reshape(x.shape)
            return self._head(
                outer["norm_final"], outer["lm_head"]["kernel"], y
            )

        def eval_step(state: TrainState, tokens):
            return {
                "loss": next_token_loss(
                    forward(state.params, tokens), tokens
                )
            }

        if self.schedule == "gpipe":

            def train_step(state: TrainState, tokens, lr):
                def loss_fn(p):
                    return next_token_loss(
                        forward(p, tokens), tokens,
                        label_smoothing=self.cfg.label_smoothing,
                    )

                loss, grads = jax.value_and_grad(loss_fn)(state.params)
                return self._apply_grads(state, grads, lr, loss)

        else:  # 1f1b
            first_fn, last_fn = self._first_last_fns()
            run_1f1b = pipeline_1f1b(
                first_fn, stage_fn, last_fn, mm, PIPE_AXIS
            )
            train_step = self._build_1f1b_train_step(
                run_1f1b, micro_spec, has_data
            )

        self._train_step = _registered_jit(
            train_step, key="pipeline.train_step", donate_argnums=0
        )
        self._eval_step = _registered_jit(eval_step,
                                          key="pipeline.eval_step")
        # every schedule exposes the same pure (state, tokens, lr) ->
        # (state, metrics) step, so superstep fusion (cfg.superstep > 1:
        # K steps in one scanned dispatch) composes with the pipeline
        # unchanged — the LMTrainer fit loop drives it
        self._build_superstep(train_step)
        trace.end(self._steps_span)

    def _first_last_fns(self):
        """The embed/loss-head halves shared by every manual-VJP
        schedule (plain 1F1B and interleaved): the embed is recomputed
        inside stage 0, the final norm + LM head + loss live inside the
        last stage (each microbatch's backward needs its loss there)."""
        model = self.model

        def first_fn(embed, tok):
            return jnp.take(embed, tok, axis=0).astype(model.dtype)

        def last_fn(last_params, y, tgt):
            logits = self._head(
                last_params["norm_final"],
                last_params["lm_head"]["kernel"],
                y,
            )
            return next_token_loss(
                logits, tgt,
                label_smoothing=self.cfg.label_smoothing,
            )

        return first_fn, last_fn

    def _build_1f1b_train_step(self, run_fn, micro_spec, has_data):
        """train_step for any 1F1B-family runner (``pipeline_1f1b`` or
        ``pipeline_interleaved`` — identical
        ``run(stages, embed, last_params, data_micro, tgt_micro)``
        contract): wraps it in the DP data-axis choreography and
        assembles the grads tree for the optimizer."""
        from tpuflow.parallel.mesh import DATA_AXIS

        mm = self.n_microbatches

        def run_wrapped(stages, embed, last_params, dm, tm):
            # gate on the AXIS EXISTING, not dp > 1: a size-1 data
            # axis still makes dm/tm (and so every schedule value)
            # data-varying, which the replicated out_specs reject
            # unless the pmean strips the vma
            if has_data:
                # per-device math over data-sharded microbatch rows:
                # tag the replicated params data-varying up front
                # (same reasoning as pipeline_1f1b's pipe pvary),
                # then mean-reduce the per-replica grads/loss
                from tpuflow.parallel.collectives import pvary

                embed = pvary(embed, DATA_AXIS)
                last_params = jax.tree.map(
                    lambda p: pvary(p, DATA_AXIS), last_params
                )
            out = run_fn(stages, embed, last_params, dm, tm)
            if has_data:
                from jax import lax

                out = jax.tree.map(
                    lambda g: lax.pmean(g, DATA_AXIS), out
                )
            return out

        def train_step(state: TrainState, tokens, lr):
            self._check_micro(tokens)
            outer = state.params["outer"]
            stages = state.params["stages"]
            tok_micro = split_microbatches(tokens, mm)
            last_params = {
                "norm_final": outer["norm_final"],
                "lm_head": outer["lm_head"],
            }
            piped = self._smap(
                run_wrapped,
                in_specs=(P(PIPE_AXIS), P(), P(),
                          micro_spec, micro_spec),
                out_specs=(P(), P(PIPE_AXIS), P(), P()),
            )
            # tokens are both the pipeline input (embedded at stage
            # 0) and the shifted next-token targets (last stage)
            loss, stage_grads, d_embed, last_grads = piped(
                stages, outer["embed"], last_params,
                tok_micro, tok_micro,
            )
            grads = {
                "outer": {
                    "embed": d_embed,
                    "norm_final": last_grads["norm_final"],
                    "lm_head": last_grads["lm_head"],
                },
                "stages": stage_grads,
            }
            return self._apply_grads(state, grads, lr, loss)

        return train_step

    def _make_steps_interleaved(self, micro_spec, has_data,
                                stage_fn) -> None:
        """Steps for schedule='interleaved': the Megatron virtual-stage
        1F1B schedule over the device-major round-robin chunk layout
        (tables precomputed and verified by
        tpuflow.parallel.interleave.build_interleaved_schedule)."""
        mm = self.n_microbatches
        n, v = self.n_stages, self.virtual_stages
        sched = build_interleaved_schedule(n, v, mm)
        fwd_sched = build_interleaved_schedule(n, v, mm, forward_only=True)

        first_fn, last_fn = self._first_last_fns()
        run_train = pipeline_interleaved(
            first_fn, stage_fn, last_fn, sched, PIPE_AXIS
        )
        run_eval = pipeline_interleaved_fwd(
            first_fn, stage_fn, fwd_sched, PIPE_AXIS
        )
        train_step = self._build_1f1b_train_step(
            run_train, micro_spec, has_data
        )

        def eval_step(state: TrainState, tokens):
            self._check_micro(tokens)
            outer = state.params["outer"]
            tok_micro = split_microbatches(tokens, mm)
            piped = self._smap(
                lambda sb, emb, mi: from_last_stage(
                    run_eval(sb, emb, mi), PIPE_AXIS
                ),
                in_specs=(P(PIPE_AXIS), P(), micro_spec),
                out_specs=micro_spec,
            )
            y = piped(state.params["stages"], outer["embed"], tok_micro)
            y = y.reshape(tokens.shape[0], *y.shape[2:])
            logits = self._head(
                outer["norm_final"], outer["lm_head"]["kernel"], y
            )
            return {"loss": next_token_loss(logits, tokens)}

        self._train_step = _registered_jit(
            train_step, key="pipeline.train_step", donate_argnums=0
        )
        self._eval_step = _registered_jit(eval_step,
                                          key="pipeline.eval_step")
        self._build_superstep(train_step)

    def _apply_grads(self, state: TrainState, grads, lr, loss):
        opt_state = set_learning_rate(state.opt_state, lr)
        updates, opt_state = self.tx.update(grads, opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            state.replace(
                step=state.step + 1, params=params, opt_state=opt_state
            ),
            {"loss": loss},
        )

    # ---- conveniences ----------------------------------------------------

    def unpipelined_params(self):
        """Reassemble the flat ``block{i}`` param tree of the plain
        TransformerLM from the trainer's stacked/stage layout — for
        packaging/inference through the standard LM surface after a
        pipelined training run."""
        if self.state is None:
            raise ValueError("no state; call init_state()/fit() first")
        params = jax.device_get(self.state.params)
        out = dict(params["outer"])
        per = self.blocks_per_stage
        stages = params["stages"]
        for row, s in enumerate(self._stage_order):
            for j in range(per):
                out[f"block{s * per + j}"] = jax.tree.map(
                    lambda a, row=row: np.asarray(a[row]),
                    stages[f"b{j}"],
                )
        return out
