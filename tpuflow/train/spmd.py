"""Auto-sharded (GSPMD) trainer: tensor parallelism via pjit.

The shard_map Trainer (tpuflow.train.trainer) is the data-parallel
parity path with the reference's Horovod design (SURVEY.md §5.8). This
is the scale-out path for models whose WEIGHTS are sharded — e.g. the
ViT family's ``nn.with_partitioning`` annotations over the mesh
``model`` axis. Instead of manual collectives:

- parameter/optimizer-state shardings are derived from the module's
  partitioning metadata (``nn.get_partition_spec``), with optimizer
  moments inheriting their parameter's sharding;
- the train step is a plain ``jax.jit`` over the (data, model) mesh
  with batch-sharded inputs; XLA's SPMD partitioner inserts and
  schedules every collective (all-reduce for the data axis, all-gather/
  reduce-scatter around the model-sharded matmuls) on ICI.

There is no Horovod analogue to cite — the reference has no tensor
parallelism at all (SURVEY.md §2c) — so this subclass reuses the
Trainer's fit/callback/LR machinery and replaces only state init, data
placement, and the jitted steps.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpuflow.core.config import TrainConfig
from tpuflow.obs import memory as _mem
from tpuflow.obs.executables import registered_jit as _registered_jit
from tpuflow.models.classifier import backbone_param_mask, stop_gradient_frozen
from tpuflow.models.preprocess import preprocess_input, random_flip
from tpuflow.parallel.mesh import DATA_AXIS
from tpuflow.train.optimizers import get_optimizer, set_learning_rate
from tpuflow.train.state import TrainState
from tpuflow.train.trainer import Trainer, _smoothed_ce


def shard_over_data(spec_tree, abstract_params, data_size: int):
    """ZeRO-style sharding: extend each leaf's PartitionSpec by splitting
    the first dimension that (a) is unsharded in the spec and (b) divides
    evenly by the data-axis size, over ``DATA_AXIS``. Leaves with no such
    dimension stay as-is (replicated over data) — correctness never
    depends on a leaf being sharded, XLA just keeps a full copy.
    """

    def one(spec, leaf):
        shape = leaf.shape
        entries = list(spec) + [None] * (len(shape) - len(spec))
        for i, dim in enumerate(shape):
            if entries[i] is None and data_size > 0 and dim % data_size == 0:
                entries[i] = DATA_AXIS
                return P(*entries)
        return spec

    return jax.tree.map(
        one, spec_tree, abstract_params, is_leaf=lambda s: isinstance(s, P)
    )


def _path_keys(path) -> tuple:
    """KeyPath → tuple of plain string keys."""
    out = []
    for k in path:
        if hasattr(k, "key"):
            out.append(str(k.key))
        elif hasattr(k, "name"):
            out.append(str(k.name))
        elif hasattr(k, "idx"):
            out.append(str(k.idx))
        else:
            out.append(str(k))
    return tuple(out)


def _specs_like(tree, param_specs, abstract_params):
    """Spec tree for a state pytree: optimizer moments inherit their
    parameter's spec; every other leaf is replicated.

    Moments are recognized by PATH SUFFIX + shape: a state leaf at
    ``('inner_state', '0', 'mu', 'backbone', 'conv', 'kernel')`` ends
    with the param path ``('backbone', 'conv', 'kernel')`` and has its
    shape. This sees through optax wrapper states — ``optax.masked``
    (the frozen-backbone optimizer) rewrites the moment tree's
    STRUCTURE (MaskedNode placeholders), so the previous
    whole-tree-structure match silently fell back to replicated,
    disabling ZeRO sharding for any masked optimizer.
    """
    from jax.tree_util import (tree_flatten_with_path, tree_map_with_path)

    flat_specs, _ = tree_flatten_with_path(
        param_specs, is_leaf=lambda x: isinstance(x, P)
    )
    flat_params, _ = tree_flatten_with_path(abstract_params)
    spec_by_path = {_path_keys(p): s for p, s in flat_specs}
    shape_by_path = {
        _path_keys(p): tuple(leaf.shape) for p, leaf in flat_params
    }

    def assign(path, leaf):
        keys = _path_keys(path)
        shape = tuple(getattr(leaf, "shape", ()) or ())
        for i in range(len(keys)):
            suf = keys[i:]
            if suf in spec_by_path and shape_by_path.get(suf) == shape:
                return spec_by_path[suf]
        return P()

    return tree_map_with_path(assign, tree)


def derive_state_shardings(mesh, boxed, abstract_state, world: int,
                           zero: Optional[str]):
    """NamedSharding tree for a TrainState from a module's boxed
    (partitioning-annotated) init shapes: params from
    ``nn.get_partition_spec``, optimizer moments inheriting their
    parameter's spec (``_specs_like``), ZeRO splitting moments (and,
    for fsdp, params) over the data axis. Shared by SpmdTrainer and
    LMTrainer's GSPMD mode — one derivation, no drift."""
    param_specs = nn.get_partition_spec(boxed)["params"]
    abstract_params = nn.unbox(boxed)["params"]
    opt_param_specs = param_specs
    if zero in ("zero1", "fsdp"):
        opt_param_specs = shard_over_data(
            param_specs, abstract_params, world
        )
        if zero == "fsdp":
            param_specs = opt_param_specs
    specs = TrainState(
        step=P(),
        params=param_specs,
        batch_stats=jax.tree.map(lambda _: P(), abstract_state.batch_stats),
        opt_state=_specs_like(
            abstract_state.opt_state, opt_param_specs, abstract_params
        ),
        rng=P(),
        plateau_factor=P(),
    )
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


class SpmdTrainer(Trainer):
    """Trainer whose step is jit-auto-sharded over a (data, model) mesh."""

    def __init__(self, model, config: Optional[TrainConfig] = None, mesh=None,
                 run=None, zero: Optional[str] = None):
        """``zero``: None (replicated state — the reference's Horovod
        semantics, where every worker holds full optimizer state,
        SURVEY.md §2c "ZeRO/FSDP: absent"), ``'zero1'`` (optimizer
        moments sharded over the data axis; XLA builds the
        reduce-scatter/all-gather pair around the update), or
        ``'fsdp'`` (params AND moments data-sharded; XLA all-gathers
        weights around each layer's use — ZeRO-3)."""
        super().__init__(model, config, mesh=mesh, run=run)
        if zero not in (None, "zero1", "fsdp"):
            raise ValueError(f"zero must be None|'zero1'|'fsdp', got {zero!r}")
        self.zero = zero
        # LR ×N scaling follows the reference's rule (P1/03:300-302):
        # N = number of data-parallel replicas, not total chips.
        self.world = self.mesh.shape[DATA_AXIS]

    def init_state(self, sample_image_shape: Sequence[int]) -> TrainState:
        cfg = self.cfg
        dummy = jnp.zeros((1, *sample_image_shape), jnp.float32)

        def make_state(rng):
            variables = self.model.init({"params": rng}, dummy, train=False)
            variables = nn.unbox(variables)
            params = variables["params"]
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                params=params,
                batch_stats=variables.get("batch_stats", {}),
                opt_state=self.tx.init(params),
                rng=jax.random.key(cfg.seed + 1),
                plateau_factor=jnp.ones((), jnp.float32),
            )

        # partition specs from the module's with_partitioning metadata
        boxed = jax.eval_shape(
            lambda r: self.model.init({"params": r}, dummy, train=False),
            jax.random.key(cfg.seed),
        )

        mask = (
            backbone_param_mask(nn.unbox(boxed)["params"])
            if getattr(self.model, "freeze_backbone", False)
            else None
        )
        self.lr0 = cfg.learning_rate
        self.param_mask = mask  # used by _make_steps to prune the backward
        self.tx = get_optimizer(
            cfg.optimizer, self.lr0, param_mask=mask,
            grad_clip_norm=cfg.grad_clip_norm, **cfg.optimizer_kwargs
        )

        abstract = jax.eval_shape(make_state, jax.random.key(cfg.seed))
        self._state_shardings = derive_state_shardings(
            self.mesh, boxed, abstract, self.mesh.shape[DATA_AXIS],
            self.zero,
        )
        self.state = _registered_jit(
            make_state, key="spmd.init_state",
            out_shardings=self._state_shardings,
        )(jax.random.key(cfg.seed))
        _mem.tag("params", {"params": self.state.params,
                            "batch_stats": self.state.batch_stats})
        _mem.tag("opt_state", self.state.opt_state)
        return self.state

    def _make_steps(self):
        model = self.model
        data_sh = NamedSharding(self.mesh, P(DATA_AXIS))
        mask = getattr(self, "param_mask", None)

        def train_step(state: TrainState, images, labels, lr):
            x = preprocess_input(images, dtype=getattr(model, "dtype", jnp.bfloat16))
            step_rng = jax.random.fold_in(state.rng, state.step)
            if self.cfg.augment_flip:
                x = random_flip(x, jax.random.fold_in(step_rng, 1))

            def loss_fn(params):
                # frozen backbone ⇒ head-only backward (see
                # stop_gradient_frozen)
                params = stop_gradient_frozen(params, mask)
                out = model.apply(
                    {"params": params, "batch_stats": state.batch_stats},
                    x,
                    train=True,
                    rngs={"dropout": step_rng},
                    mutable=["batch_stats"],
                )
                logits, new_vars = out
                loss = _smoothed_ce(
                    logits, labels, self.cfg.label_smoothing
                )
                return loss, (logits, new_vars)

            # global-batch mean loss ⇒ gradients are already averaged
            # across the data axis; XLA emits the all-reduce.
            (loss, (logits, new_vars)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params)
            acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
            opt_state = set_learning_rate(state.opt_state, lr)
            updates, opt_state = self.tx.update(grads, opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            new_state = state.replace(
                step=state.step + 1,
                params=params,
                batch_stats=new_vars.get("batch_stats", state.batch_stats),
                opt_state=opt_state,
            )
            return new_state, {"loss": loss, "accuracy": acc}

        def eval_step(state: TrainState, images, labels):
            x = preprocess_input(images, dtype=getattr(model, "dtype", jnp.bfloat16))
            logits = model.apply(
                {"params": state.params, "batch_stats": state.batch_stats},
                x,
                train=False,
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), labels
            ).mean()
            acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
            return {"loss": loss, "accuracy": acc}

        # out_shardings pins the new state to the same layout as the
        # input state — without it XLA may pick a different output
        # sharding (observed under ZeRO), breaking the next call's
        # in_shardings contract.
        replicated = NamedSharding(self.mesh, P())
        self._train_step = _registered_jit(
            train_step, key="spmd.train_step",
            in_shardings=(self._state_shardings, data_sh, data_sh, None),
            out_shardings=(
                self._state_shardings,
                {"loss": replicated, "accuracy": replicated},
            ),
            donate_argnums=0,
        )
        self._eval_step = _registered_jit(
            eval_step, key="spmd.eval_step",
            in_shardings=(self._state_shardings, data_sh, data_sh),
        )
