"""Functional train state: params + BN stats + optimizer state + step.

≙ the mutable Keras model+optimizer the reference trains
(P1/02_model_training_single_node.py:198-215); here it is one immutable
pytree threaded through a jitted step — the donation-friendly XLA shape.
"""

from __future__ import annotations

from typing import Any

import jax
from flax import struct


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    rng: jax.Array
    # host-steered LR state that must survive checkpoint/resume: the
    # cumulative ReduceLROnPlateau factor (resume at the reduced LR, not
    # the schedule's full LR)
    plateau_factor: jax.Array

    def num_params(self) -> int:
        return sum(int(p.size) for p in jax.tree.leaves(self.params))
