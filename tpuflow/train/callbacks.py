"""Epoch-level callbacks (C9, C17).

The Keras/Horovod callback roster the reference wires up
(P1/03_model_training_distributed.py:304-322, P2/02:206-211,
P2/03:397-401), re-expressed for the functional trainer:

- broadcast-init and metric averaging are NOT callbacks here — they are
  structural (single seeded init replicated via sharding; pmean inside
  the jitted step), which is the TPU-native way;
- ReduceLROnPlateau / EarlyStopping / ModelCheckpoint / History remain
  host-side epoch hooks, same ordering rules as Keras.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class Callback:
    def set_trainer(self, trainer) -> None:
        self.trainer = trainer

    def on_train_begin(self) -> None: ...

    def on_epoch_end(self, epoch: int, logs: Dict[str, float]) -> None: ...

    def on_superstep_end(self, global_step: int, metrics) -> None:
        """Fused-dispatch cadence hook (TrainConfig.superstep > 1): runs
        once per K-step scan block with the global step AFTER the block
        and the block's DEVICE-RESIDENT stacked metrics (dict of (k,)
        arrays). Deliberately not a per-step hook — superstep mode
        exists to eliminate per-step host round-trips, so a callback
        that fetches here pays one sync per block, not per step. The
        epoch-level hooks above are unaffected (blocks never cross an
        epoch boundary)."""
        ...

    def on_train_end(self) -> None: ...


class History(Callback):
    def __init__(self):
        self.history: Dict[str, List[float]] = {}

    def on_epoch_end(self, epoch, logs):
        for k, v in logs.items():
            self.history.setdefault(k, []).append(v)


class ReduceLROnPlateau(Callback):
    """≙ keras ReduceLROnPlateau(monitor='val_loss', patience, factor)
    (P1/03:319-322). Mutates the trainer's LRController."""

    def __init__(
        self,
        monitor: str = "val_loss",
        factor: float = 0.1,
        patience: int = 10,
        min_delta: float = 1e-4,
        verbose: bool = False,
    ):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.min_delta = min_delta
        self.verbose = verbose
        self.best = float("inf")
        self.wait = 0

    def on_epoch_end(self, epoch, logs):
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if cur < self.best - self.min_delta:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                new_lr = self.trainer.lr_controller.reduce(self.factor)
                self.wait = 0
                # persist into TrainState so checkpoints resume at the
                # reduced LR
                import jax.numpy as jnp

                self.trainer.state = self.trainer.state.replace(
                    plateau_factor=jnp.asarray(
                        self.trainer.lr_controller.plateau_factor, jnp.float32
                    )
                )
                if self.verbose:
                    # peak LR: under cosine decay the per-step value
                    # additionally follows the anneal (lr.py:reduce)
                    print(f"ReduceLROnPlateau: peak lr -> {new_lr:.3e}")


class EarlyStopping(Callback):
    """≙ keras EarlyStopping (P2/03:397-401)."""

    def __init__(self, monitor: str = "val_loss", patience: int = 3, min_delta: float = 0.0):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.best = float("inf")
        self.wait = 0

    def on_epoch_end(self, epoch, logs):
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if cur < self.best - self.min_delta:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.trainer.stop_training = True


class ModelCheckpoint(Callback):
    """Per-epoch checkpoint; only the PRIMARY process writes files
    (≙ rank-0-only ModelCheckpoint to {dir}/checkpoint-{epoch}.ckpt,
    P2/02:206-211). When the saved leaves are cross-process-sharded
    (ZeRO/FSDP), every process enters save_checkpoint — assembling the
    state is a collective; do NOT re-add an is_primary() gate around
    the call or the primary deadlocks in the allgather.

    Default saves the FULL TrainState (params + optimizer state + step +
    LR state) so resume is exact — the capability the reference lacks;
    ``save_weights_only=True`` gives the reference's weights-only files.
    """

    def __init__(self, checkpoint_dir: str, save_weights_only: bool = False,
                 async_write: bool = False):
        self.checkpoint_dir = checkpoint_dir
        self.save_weights_only = save_weights_only
        # async_write: the host fetch stays synchronous here (it is a
        # snapshot AND, for ZeRO state, a collective), the serialize +
        # atomic write overlaps the next epoch (ckpt.AsyncCheckpointer)
        self._async = None
        if async_write:
            from tpuflow.ckpt import AsyncCheckpointer

            self._async = AsyncCheckpointer()

    def on_epoch_end(self, epoch, logs):
        from tpuflow.core import is_primary
        from tpuflow.ckpt import save_checkpoint
        from tpuflow.ckpt.checkpoint import is_cross_process_sharded

        # ZeRO/FSDP state is assembled by a collective — every process
        # must participate; only the primary writes (inside
        # save_checkpoint). Gate on the leaves actually saved: a
        # weights-only save of a ZeRO run ships replicated params, so
        # non-primary processes have nothing to contribute or fetch.
        state = self.trainer.state
        saved = (
            (state.params, state.batch_stats)
            if self.save_weights_only
            else state
        )
        if not is_primary() and not is_cross_process_sharded(saved):
            return
        from tpuflow.obs import trace

        with trace.span("train.checkpoint", phase="checkpoint",
                        epoch=epoch):
            if self._async is not None:
                self._async.save(
                    self.checkpoint_dir, state, step=epoch + 1,
                    weights_only=self.save_weights_only,
                )
                self._gc()
                return
            wrote = save_checkpoint(
                self.checkpoint_dir,
                state,
                step=epoch + 1,
                weights_only=self.save_weights_only,
            )
        self._gc(just_wrote=wrote)

    def _gc(self, just_wrote=None) -> None:
        """Retention (ISSUE 10 satellite): cfg.keep_last_checkpoints
        caps both checkpoint namespaces after each save — the newest
        VALID checkpoint is never deleted (gc_checkpoints' rail;
        ``just_wrote`` spares it re-reading the file this save
        produced — async saves pass None, the write may be in
        flight)."""
        keep = getattr(
            getattr(self.trainer, "cfg", None),
            "keep_last_checkpoints", None,
        )
        if keep:
            from tpuflow.ckpt.checkpoint import gc_checkpoints

            gc_checkpoints(self.checkpoint_dir, keep,
                           just_wrote=just_wrote)

    def on_train_end(self):
        if self._async is not None:
            self._async.wait()


class TrackingCallback(Callback):
    """Autolog per-epoch metrics into a tracking run, primary-only
    (≙ mlflow autolog / rank-0 log_metric, P1/02:195, P1/03:360-373)."""

    def __init__(self, run, log_lr: bool = True):
        self.run = run
        self.log_lr = log_lr

    def on_epoch_end(self, epoch, logs):
        from tpuflow.core import is_primary

        if not is_primary() or self.run is None:
            return
        for k, v in logs.items():
            self.run.log_metric(k, float(v), step=epoch)


class MetricsLogger(Callback):
    """Run-scoped persistence of the METRICS PLANE (ISSUE 5), primary-
    only: each epoch, every gauge/counter/histogram summary from
    :mod:`tpuflow.obs.gauges` (windowed percentiles primary, ``_cum``
    cumulative) lands in the tracking run as step-stamped metrics, and
    — when the :mod:`tpuflow.obs.timeseries` default ring is ticking —
    the ring itself is archived as a JSON artifact
    (``metrics_plane/epoch_NNNN.json``). This is the live half of the
    reference's MLflow role: serve and trainer operational numbers
    stored BESIDE the run's params/losses, so a post-hoc reader gets
    the same picture a scraper had. ``tick=True`` (default) also ticks
    the ring each epoch, so epoch cadence produces windowed deltas
    even without the interval thread."""

    def __init__(self, run, prefix: Optional[str] = None,
                 artifacts: bool = True, tick: bool = True):
        self.run = run
        self.prefix = prefix
        self.artifacts = artifacts
        self.tick = tick

    def on_epoch_end(self, epoch, logs):
        from tpuflow.core import is_primary

        if not is_primary() or self.run is None:
            return
        from tpuflow.obs import timeseries

        ring = timeseries.default_ring()
        if ring is None and self.tick:
            ring = timeseries.start(thread=False)
        if ring is not None and self.tick:
            ring.tick()
        self.run.log_gauges(self.prefix, step=epoch)
        if self.artifacts and ring is not None:
            self.run.log_dict(
                ring.export(), f"metrics_plane/epoch_{epoch:04d}.json"
            )


class SystemMetricsCallback(Callback):
    """Per-epoch host/device utilization into the tracking run,
    primary-only (≙ the Ganglia dashboards the reference points
    operators at, P1/04:25-30, but recorded WITH the run so they
    outlive the cluster). Keys come pre-namespaced from
    sample_system_metrics (``sys.*`` host, ``device<i>.*`` HBM)."""

    def __init__(self, run, include_devices: bool = True):
        self.run = run
        self.include_devices = include_devices

    def on_epoch_end(self, epoch, logs):
        from tpuflow.core import is_primary
        from tpuflow.obs.sysmetrics import sample_system_metrics

        if not is_primary() or self.run is None:
            return
        for k, v in sample_system_metrics(self.include_devices).items():
            self.run.log_metric(k, float(v), step=epoch)


class ReplicaConsistencyCheck(Callback):
    """Every N epochs, assert the replicated-state invariants: all
    devices hold bitwise-identical replicated params, all processes
    agree on a state checksum, and params are finite — the testable
    form of the reference's unchecked broadcast-init guarantee
    (P1/03:305-308; SURVEY.md §5.2)."""

    def __init__(self, every_n_epochs: int = 1, check_nans: bool = True):
        self.every = max(1, every_n_epochs)
        self.check_nans = check_nans

    def on_epoch_end(self, epoch, logs):
        if (epoch + 1) % self.every:
            return
        from tpuflow.core.debug import (
            assert_consistent_across_processes,
            assert_replicated_across_devices,
            nan_check,
        )

        params = self.trainer.state.params
        assert_replicated_across_devices(params, "params")
        assert_consistent_across_processes(params, "params")
        if self.check_nans:
            nan_check(params, "params")
