"""Shared SIGTERM preemption machinery for the trainers (r05).

One context manager serves both ``Trainer.fit`` and ``LMTrainer.fit``:
it yields a mutable ``{"hit": bool}`` flag that a SIGTERM flips — the
handler does nothing else; all device/filesystem work happens in the
trainer's loop context — and restores the previous handler on exit,
exceptions included. The gates live here so the two fit loops cannot
drift apart:

- multi-process: DISABLED with a warning. A per-process stop flag
  breaks the identical-collective-schedule invariant (processes
  stopping at different steps → mismatched pmeans → deadlock);
  multi-process preemption stays at gang granularity (launcher
  ``--restarts`` + epoch checkpoints — tests/test_multiproc_killresume
  proves that path) until a synchronized agreement step exists.
- non-main thread: DISABLED with a warning (``signal.signal`` is a
  main-thread-only API). A threaded HPO driver believing its trials
  are preemption-safe must hear otherwise.
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def sigterm_preempt_flag(enabled: bool):
    flag = {"hit": False}
    if not enabled:
        yield flag
        return
    import signal
    import threading
    import warnings

    import jax

    if jax.process_count() > 1:
        warnings.warn(
            "checkpoint_on_preempt is single-process only for now; "
            "multi-process runs keep gang-restart semantics "
            "(--restarts + epoch checkpoints)", stacklevel=3,
        )
        yield flag
        return
    if threading.current_thread() is not threading.main_thread():
        warnings.warn(
            "checkpoint_on_preempt needs fit() on the MAIN thread "
            "(signal.signal is main-thread-only); preemption "
            "protection is DISABLED for this run", stacklevel=3,
        )
        yield flag
        return
    old = signal.signal(
        signal.SIGTERM, lambda *_a: flag.__setitem__("hit", True)
    )
    try:
        yield flag
    finally:
        signal.signal(signal.SIGTERM, old)
