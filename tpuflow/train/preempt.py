"""Shared SIGTERM preemption machinery for the trainers (r05).

One context manager serves both ``Trainer.fit`` and ``LMTrainer.fit``:
it yields a mutable ``{"hit": bool}`` flag that a SIGTERM flips — the
handler does nothing else; all device/filesystem work happens in the
trainer's loop context — and restores the previous handler on exit,
exceptions included.

Single-process: the loop checks the local flag every step.

Multi-process: a per-process flag alone would break the identical-
collective-schedule invariant (processes stopping at different steps →
mismatched pmeans → deadlock), so the loop instead calls
:func:`should_stop` at a fixed step cadence
(``TrainConfig.preempt_sync_every``) — an OR-reduction of EVERY
host's flag (allgather + max), so every process takes the stop
decision at the SAME global step. Any-host semantics matter: per-VM
spot reclamation SIGTERMs only the host being reclaimed, and a
primary-only rule would sleep through exactly the notices the feature
exists for.

Non-main thread: DISABLED with a warning (``signal.signal`` is a
main-thread-only API). A threaded HPO driver believing its trials are
preemption-safe must hear otherwise.
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def sigterm_preempt_flag(enabled: bool):
    flag = {"hit": False}
    if not enabled:
        yield flag
        return
    import signal
    import threading
    import warnings

    if threading.current_thread() is not threading.main_thread():
        warnings.warn(
            "checkpoint_on_preempt needs fit() on the MAIN thread "
            "(signal.signal is main-thread-only); preemption "
            "protection is DISABLED for this run", stacklevel=3,
        )
        yield flag
        return
    old = signal.signal(
        signal.SIGTERM, lambda *_a: flag.__setitem__("hit", True)
    )
    try:
        yield flag
    finally:
        signal.signal(signal.SIGTERM, old)


def agree_on_preempt(flag: dict) -> bool:
    """Multi-process stop agreement: OR-reduce every host's flag
    (allgather + max) — ANY host's SIGTERM stops the whole gang at the
    same step (per-VM spot reclamation signals only the reclaimed
    host). The reduction is itself a collective: call it at the SAME
    step on every process (the trainers' lockstep loops guarantee
    this via :func:`should_stop`)."""
    import numpy as np
    from jax.experimental import multihost_utils

    vals = multihost_utils.process_allgather(
        np.int32(1 if flag["hit"] else 0)
    )
    return bool(np.max(vals))


def should_stop(flag: dict, global_step: int, sync_every: int,
                multiprocess: bool) -> bool:
    """THE per-step stop decision, shared by both fit loops so their
    cadence logic can never drift: single-process reads the local flag
    every step; multi-process agrees collectively every
    ``sync_every``-th global step."""
    if not multiprocess:
        return bool(flag["hit"])
    if global_step % max(1, int(sync_every)):
        return False
    return agree_on_preempt(flag)


def agree_on_world(desired: int) -> int:
    """Multi-process agreement on an elastic-resize target (ISSUE 10):
    all-process MIN of each host's desired data-parallel world — the
    conservative merge (a host that lost a replica wins over hosts
    that have not noticed yet), and, like :func:`agree_on_preempt`, a
    COLLECTIVE: call it at the same block boundary on every process
    (ElasticController does)."""
    import numpy as np
    from jax.experimental import multihost_utils

    vals = multihost_utils.process_allgather(np.int32(desired))
    return int(np.min(vals))


def superstep_sizes(n_steps: int, K: int, step0: int,
                    sync_every: int = 0) -> list:
    """Chunk ``n_steps`` (starting at global step ``step0``) into
    superstep block sizes <= K such that a block never crosses a
    preemption agreement point (multiples of ``sync_every`` when > 0):
    K is auto-lowered at the boundaries, so every cadence the K=1 loop
    honors per step lands on a block edge. Shared by Trainer and
    LMTrainer so their block boundaries (and thus the multi-process
    collective agreement schedule) can never drift apart."""
    sizes = []
    g, left = step0, int(n_steps)
    K = max(1, int(K))
    while left > 0:
        k = min(K, left)
        if sync_every > 0:
            to_sync = (-g) % sync_every or sync_every
            k = min(k, to_sync)
        sizes.append(k)
        g += k
        left -= k
    return sizes
