"""Data-parallel trainer — the heart of the framework (C8-C10).

Replaces the reference's ``train_and_evaluate_hvd`` stack
(P1/03_model_training_distributed.py:282-375): Horovod's
DistributedOptimizer/broadcast/metric-average machinery becomes ONE
jitted, shard_map-decorated train step over a ``Mesh``:

- gradient sync: ``lax.pmean`` inside the step (≙ DistributedOptimizer
  ring-allreduce, P1/03:302) — XLA schedules/fuses/overlaps it on ICI;
- consistent init: single seeded init, state replicated via sharding
  (≙ BroadcastGlobalVariablesCallback(0), P1/03:305-308);
- metric averaging: ``lax.pmean`` on step metrics (≙
  MetricAverageCallback, P1/03:310-313);
- LR scale × world size + per-batch warmup + plateau: host-side
  LRController feeding a traced scalar (P1/03:300-302,315-322);
- BN statistics: cross-replica pmean when the backbone trains (an
  upgrade over Horovod's local-only BN stats);
- world-size-1 debug mode ≙ HorovodRunner(np=-1) (P1/03:385-397): the
  same code on a 1-device mesh.

Everything under jit is static-shaped; batches stream in uint8 and are
scaled to [-1,1] on device so the host→device link carries 4x less.
"""

from __future__ import annotations

import collections
from functools import partial
from typing import Any, Dict, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P
from tpuflow.core.compat import shard_map

from tpuflow.core.config import TrainConfig
from tpuflow.obs import memory as _mem
from tpuflow.obs import trace
from tpuflow.obs.executables import registered_jit as _registered_jit
from tpuflow.models.classifier import backbone_param_mask, stop_gradient_frozen
from tpuflow.models.preprocess import preprocess_input, random_flip
from tpuflow.parallel.mesh import DATA_AXIS, build_mesh, world_size
from tpuflow.train.callbacks import Callback, History
from tpuflow.train.lr import LRController
from tpuflow.train.optimizers import get_optimizer, set_learning_rate
from tpuflow.train.state import TrainState


def _smoothed_ce(logits, labels, smoothing: float):
    """Training cross-entropy with optional label smoothing (the
    standard regularizer the reference lacks). smoothing=0.0 is exactly
    ``softmax_cross_entropy_with_integer_labels`` — the parity path.
    Eval losses stay unsmoothed so val_loss is comparable across
    smoothing settings."""
    if not 0.0 <= smoothing < 1.0:
        raise ValueError(f"label_smoothing must be in [0, 1), got {smoothing}")
    logits = logits.astype(jnp.float32)
    if smoothing:
        one_hot = jax.nn.one_hot(labels, logits.shape[-1])
        targets = optax.smooth_labels(one_hot, smoothing)
        return optax.softmax_cross_entropy(logits, targets).mean()
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels
    ).mean()


class Trainer:
    def __init__(
        self,
        model,
        config: Optional[TrainConfig] = None,
        mesh=None,
        run=None,
    ):
        self.model = model
        self.cfg = config or TrainConfig()
        if getattr(self.cfg, "grad_accum_steps", 1) != 1:
            raise ValueError(
                "grad_accum_steps is honored by LMTrainer only; "
                "Trainer updates once per batch — lower the batch "
                "size or use the LM family"
            )
        self.mesh = mesh if mesh is not None else build_mesh()
        self.world = world_size(self.mesh)
        self.run = run  # tracking run (primary-only effects)
        self.tx = None
        self.state: Optional[TrainState] = None
        self.stop_training = False
        self.lr_controller: Optional[LRController] = None
        self._train_step = None
        self._eval_step = None
        self.health = None  # HealthMonitor, armed per-fit (cfg.watchdog)

    # ---- initialization --------------------------------------------------

    def init_state(self, sample_image_shape: Sequence[int]) -> TrainState:
        """Seeded init, replicated across the mesh.

        Every process calls this with the same seed so parameters are
        bitwise identical — the broadcast-init invariant (P1/03:305-308)
        holds by construction and is asserted in tests (SURVEY.md §5.2).
        """
        rng = jax.random.key(self.cfg.seed)
        dummy = jnp.zeros((1, *sample_image_shape), jnp.float32)
        variables = self.model.init({"params": rng}, dummy, train=False)
        # strip nn.with_partitioning boxes (e.g. the ViT family's TP
        # annotations) — the shard_map DP path replicates params
        import flax.linen as _nn

        variables = _nn.unbox(variables)
        weights = getattr(self.model, "weights", None)
        if weights:
            # pretrained backbone (≙ Keras weights='imagenet',
            # P1/02:164-169): replace the randomly initialized backbone
            # with the converted checkpoint; head stays fresh
            from tpuflow.models.pretrained import load_backbone_variables

            variables = load_backbone_variables(variables, weights)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        mask = (
            backbone_param_mask(params)
            if getattr(self.model, "freeze_backbone", False)
            else None
        )
        # kept for _make_steps: frozen leaves are stop_gradient'ed inside
        # the loss so XLA never builds the backbone backward at all —
        # masking only at the optimizer would still pay full backprop
        # FLOPs and allreduce bandwidth for gradients it then discards
        self.param_mask = mask
        self.lr0 = self.cfg.learning_rate
        self.tx = get_optimizer(
            self.cfg.optimizer,
            self.lr0,
            param_mask=mask,
            grad_clip_norm=self.cfg.grad_clip_norm,
            **self.cfg.optimizer_kwargs,
        )
        state = TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=batch_stats,
            opt_state=self.tx.init(params),
            rng=jax.random.key(self.cfg.seed + 1),
            plateau_factor=jnp.ones((), jnp.float32),
        )
        from tpuflow.parallel.mesh import replicate_tree

        # multi-process-safe replication (device_put cannot target
        # non-addressable meshes); host state is identical on every
        # process by seeded construction
        self.state = replicate_tree(state, self.mesh)
        self._tag_state()
        return self.state

    def _tag_state(self) -> None:
        """Device-buffer ledger tags (ISSUE 7): params/opt_state by
        component. Donation replaces the state's arrays every step, so
        fit re-tags at epoch boundaries — mid-epoch the current state
        shows up as ``untagged`` residual, which is accurate enough
        for the per-epoch accounting the ledger serves."""
        if self.state is None:
            return
        _mem.tag("params", {"params": self.state.params,
                            "batch_stats": getattr(self.state,
                                                   "batch_stats", {})})
        _mem.tag("opt_state", self.state.opt_state)

    # ---- jitted steps ----------------------------------------------------

    def _make_steps(self):
        mesh = self.mesh
        model = self.model
        mask = getattr(self, "param_mask", None)
        # watchdog mode (ISSUE 5): non-finite flag + grad norm join the
        # metrics block on device (zero extra syncs; default off so
        # parity runs keep the exact legacy program)
        watch = bool(getattr(self.cfg, "watchdog", False))

        def train_step(state: TrainState, images, labels, lr):
            x = preprocess_input(images, dtype=getattr(model, "dtype", jnp.bfloat16))
            step_rng = jax.random.fold_in(state.rng, state.step)
            step_rng = jax.random.fold_in(step_rng, jax.lax.axis_index(DATA_AXIS))
            if self.cfg.augment_flip:
                x = random_flip(x, jax.random.fold_in(step_rng, 1))

            def loss_fn(params):
                # frozen backbone ⇒ head-only backward (XLA DCEs the
                # backbone backward — ~2x step FLOPs on the flagship)
                params = stop_gradient_frozen(params, mask)
                out = model.apply(
                    {"params": params, "batch_stats": state.batch_stats},
                    x,
                    train=True,
                    rngs={"dropout": step_rng},
                    mutable=["batch_stats"],
                )
                logits, new_vars = out
                loss = _smoothed_ce(
                    logits, labels, self.cfg.label_smoothing
                )
                return loss, (logits, new_vars)

            (loss, (logits, new_vars)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params)
            # ≙ hvd.DistributedOptimizer: mean-allreduce gradients
            # (P1/03:302). Frozen leaves are identically zero — rebuild
            # them from the replicated params (right vma for the P()
            # out_spec) instead of paying pmean bandwidth on zeros.
            if mask is not None:
                grads = jax.tree_util.tree_map(
                    lambda g, p, m: (
                        jax.lax.pmean(g, DATA_AXIS) if m else jnp.zeros_like(p)
                    ),
                    grads, state.params, mask,
                )
            else:
                grads = jax.lax.pmean(grads, DATA_AXIS)
            # ≙ MetricAverageCallback: average metrics across replicas (P1/03:313)
            acc = jnp.mean(
                (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
            )
            metrics = jax.lax.pmean(
                {"loss": loss, "accuracy": acc}, DATA_AXIS
            )
            if watch:
                # grads are already pmean'd (replicated) here, so the
                # norm and the flag are too — no extra collective
                gn = optax.global_norm(grads)
                metrics = dict(metrics)
                metrics["grad_norm"] = gn
                metrics["nonfinite"] = jnp.logical_not(
                    jnp.isfinite(metrics["loss"]) & jnp.isfinite(gn)
                ).astype(jnp.float32)
            new_bs = new_vars.get("batch_stats", state.batch_stats)
            # cross-replica BN stats (upgrade over Horovod local stats)
            new_bs = jax.lax.pmean(new_bs, DATA_AXIS)
            opt_state = set_learning_rate(state.opt_state, lr)
            updates, opt_state = self.tx.update(grads, opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            new_state = state.replace(
                step=state.step + 1,
                params=params,
                batch_stats=new_bs,
                opt_state=opt_state,
            )
            return new_state, metrics

        def eval_step(state: TrainState, images, labels):
            x = preprocess_input(images, dtype=getattr(model, "dtype", jnp.bfloat16))
            logits = model.apply(
                {"params": state.params, "batch_stats": state.batch_stats},
                x,
                train=False,
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), labels
            ).mean()
            acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
            return jax.lax.pmean({"loss": loss, "accuracy": acc}, DATA_AXIS)

        train_sm = shard_map(
            train_step,
            mesh=mesh,
            in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P()),
            out_specs=(P(), P()),
        )
        eval_sm = shard_map(
            eval_step,
            mesh=mesh,
            in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=P(),
        )
        self._train_step = _registered_jit(train_sm,
                                           key="trainer.train_step",
                                           donate_argnums=0)
        self._eval_step = _registered_jit(eval_sm, key="trainer.eval_step")

        # superstep program (cfg.superstep > 1): K chained train steps
        # inside ONE jitted lax.scan over a stacked (K, batch, ...)
        # block — one host dispatch per K steps, per-step metrics
        # accumulated into a device-resident (K,) block. The scan body
        # is the SAME train_sm as the per-step path (per-step RNG folds
        # on state.step, carried in the scan), so per-step losses and
        # params match the K=1 loop — bitwise under a fixed compilation
        # config (tests/test_superstep.py); XLA may fuse the body
        # differently at high opt levels (recompile-class ulp noise).
        # Tracing is lazy: K=1 runs never touch this.
        def superstep(state, images, labels, lrs):
            def body(c, x):
                im, lb, lr = x
                return train_sm(c, im, lb, lr)

            return jax.lax.scan(body, state, (images, labels, lrs))

        self._superstep = _registered_jit(superstep,
                                          key="trainer.superstep",
                                          donate_argnums=0)

    # ---- data movement ---------------------------------------------------

    def _put(self, batch: Dict[str, np.ndarray]):
        """Local numpy batch → global batch-sharded device arrays."""
        sharding = NamedSharding(self.mesh, P(DATA_AXIS))
        n_data = self.mesh.shape[DATA_AXIS]
        local = batch["image"].shape[0]
        if (local * jax.process_count()) % n_data != 0:
            raise ValueError(
                f"global batch {local * jax.process_count()} not divisible by "
                f"mesh data axis {n_data}; choose batch_size as a multiple of "
                f"devices-per-process (= {n_data // jax.process_count()})"
            )
        images = jax.make_array_from_process_local_data(sharding, batch["image"])
        labels = jax.make_array_from_process_local_data(sharding, batch["label"])
        return images, labels

    def _put_block(self, batches: List[Dict[str, np.ndarray]]):
        """K stacked local batches → one global (K, batch, ...) block,
        batch-sharded on dim 1 (the scan's per-step slice shards exactly
        like a ``_put`` batch)."""
        return self._put_block_stacked(
            np.stack([b["image"] for b in batches]),
            np.stack([b["label"] for b in batches]),
        )

    def _put_block_stacked(self, images_np: np.ndarray,
                           labels_np: np.ndarray):
        sharding = NamedSharding(self.mesh, P(None, DATA_AXIS))
        n_data = self.mesh.shape[DATA_AXIS]
        local = images_np.shape[1]
        if (local * jax.process_count()) % n_data != 0:
            raise ValueError(
                f"global batch {local * jax.process_count()} not divisible by "
                f"mesh data axis {n_data}; choose batch_size as a multiple of "
                f"devices-per-process (= {n_data // jax.process_count()})"
            )
        images = jax.make_array_from_process_local_data(sharding, images_np)
        labels = jax.make_array_from_process_local_data(sharding, labels_np)
        return images, labels

    @staticmethod
    def _staging_depth(ds) -> int:
        """Device-put staging depth: follow the dataset's own
        ``prefetch`` knob so the loader's host queue and the trainer's
        in-flight H2D count describe the SAME pipeline — the old
        hardcoded depth=2 silently disagreed with any non-default
        Dataset(prefetch=...). Capped at 4: ``prefetch`` is a
        HOST-queue throughput knob, and letting a large value pin that
        many full batches in device memory would turn it into a silent
        HBM-footprint knob (a 256x224² batch is ~38 MB; nothing past
        double-buffering-with-headroom helps the device anyway)."""
        return min(4, max(1, int(getattr(ds, "prefetch", 2) or 2)))

    def _prefetch(self, it: Iterable, depth: int = 2,
                  component: str = "data_staging"):
        """Device-put ahead of compute: double-buffered H2D (N5).

        Span accounting: the host batch pull and the H2D put are the
        two data_wait leaves; the consumer's ``next()`` on this
        generator executes them, so the fit loop does not re-wrap it
        (that would double-count the phase). Staged buffers are tagged
        ``component`` in the device-buffer ledger (eval feeds pass
        ``"eval"``)."""
        it = iter(it)
        buf: collections.deque = collections.deque()
        while True:
            with trace.span("train.data_wait", phase="data_wait"):
                batch = next(it, None)
            if batch is None:
                break
            with trace.span("train.device_put", phase="data_wait"):
                put = self._put(batch)
                _mem.tag(component, put)
                buf.append(put)
            if len(buf) >= depth:
                yield buf.popleft()
        while buf:
            yield buf.popleft()

    def _stage_superstep(self, host_iter, sizes, depth: int = 2):
        """Superstep block staging with double buffering: yields
        ``(k, images, labels)`` device blocks following the ``sizes``
        schedule. With depth >= 2, block i+1 is assembled and
        ``device_put`` while the device still executes block i (the
        consumer dispatches asynchronously) — the H2D link never sits
        behind the scan. Each host batch is copied into the stacked
        block array AS IT IS PULLED (not held and np.stack'ed at the
        end): the loader's reuse ring (data/loader.py) sizes its
        buffer pool for ONE batch outstanding at the consumer, and
        holding K un-copied batches would let the decode thread
        recycle a slot still referenced by the block — silent pixel
        corruption. Same total copy work as np.stack, safe ordering.
        A dried-up host stream yields a final SHORT block
        (k < scheduled) and stops."""
        buf: collections.deque = collections.deque()
        for want in sizes:
            images = labels = None
            got = 0
            with trace.span("train.data_wait", phase="data_wait",
                            k=want):
                for j in range(want):
                    try:
                        b = next(host_iter)
                    except StopIteration:
                        break
                    if images is None:
                        images = np.empty((want, *b["image"].shape),
                                          b["image"].dtype)
                        labels = np.empty((want, *b["label"].shape),
                                          b["label"].dtype)
                    images[j] = b["image"]  # copy NOW (ring safety)
                    labels[j] = b["label"]
                    got += 1
            if got:
                with trace.span("train.device_put", phase="data_wait",
                                k=got):
                    blk = self._put_block_stacked(images[:got],
                                                  labels[:got])
                    _mem.tag("data_staging", blk)
                    buf.append((got, *blk))
            if got < want:
                break
            if len(buf) >= depth:
                yield buf.popleft()
        while buf:
            yield buf.popleft()

    @staticmethod
    def _superstep_sizes(n_steps: int, K: int, step0: int,
                         sync_every: int = 0) -> List[int]:
        from tpuflow.train.preempt import superstep_sizes

        return superstep_sizes(n_steps, K, step0, sync_every)

    # ---- fit/evaluate ----------------------------------------------------

    def maybe_resume(self, checkpoint_dir: Optional[str] = None,
                     steps_per_epoch: Optional[int] = None) -> int:
        """Restore the newest checkpoint in ``checkpoint_dir`` (default:
        cfg.checkpoint_dir) into ``self.state`` and return the epoch to
        continue from — 0 when there is nothing to resume.

        This is the restart half of the failure story the reference
        lacks (SURVEY.md §5.3-5.4: gang-fail → relaunch → restore): a
        relaunched job calls fit(initial_epoch=maybe_resume()) with the
        same command line and continues where it stopped. The filename
        number (``checkpoint-{n}.ckpt``, the reference's layout at
        P2/02:206-211) is the count of COMPLETED epochs — which is
        exactly the next 0-based epoch index.

        With ``steps_per_epoch``, mid-epoch PREEMPTION checkpoints
        (``checkpoint-step-{N}.ckpt``, cfg.checkpoint_on_preempt) are
        also considered, compared in global-step units; when one is
        newest, the position within the epoch is stashed as
        ``self._resume_skip_steps`` and the next ``fit`` call
        fast-forwards the stream by that many batches — EXACT resume.
        Without ``steps_per_epoch``, step checkpoints are ignored
        (epoch-boundary semantics, as before).
        """
        import re

        from tpuflow.ckpt import (latest_checkpoint, latest_resume_point,
                                  restore_into_state)

        ckdir = checkpoint_dir or self.cfg.checkpoint_dir
        self._resume_skip_steps = 0
        if not ckdir:
            return 0
        if steps_per_epoch is not None:
            found = latest_resume_point(ckdir, int(steps_per_epoch))
            if found is None:
                return 0
            path, epoch, skip = found
            if self.state is None:
                raise RuntimeError("call init_state() before maybe_resume()")
            self.state = restore_into_state(path, self.state)
            self._resume_skip_steps = skip
            self._resume_epoch = epoch
            return epoch
        path = latest_checkpoint(ckdir)
        if path is None:
            return 0
        if self.state is None:
            raise RuntimeError("call init_state() before maybe_resume()")
        self.state = restore_into_state(path, self.state)
        m = re.search(r"checkpoint-(\d+)\.ckpt$", path)
        return int(m.group(1)) if m else 0

    def fit(
        self,
        train_ds,
        val_ds=None,
        epochs: Optional[int] = None,
        steps_per_epoch: Optional[int] = None,
        validation_steps: Optional[int] = None,
        callbacks: Optional[List[Callback]] = None,
        initial_epoch: int = 0,
        verbose: bool = False,
    ) -> History:
        """≙ model.fit(...) with the Horovod callback roster (P1/03:340-358).

        Epochs are fixed step counts over an infinite sharded stream —
        every worker executes identical collective schedules
        (P1/03:197-200,350-351).
        """
        cfg = self.cfg
        epochs = epochs if epochs is not None else cfg.epochs
        steps_per_epoch = steps_per_epoch or train_ds.steps_per_epoch()
        if getattr(cfg, "superstep", 1) < 1:
            raise ValueError(
                f"superstep must be >= 1, got {cfg.superstep}"
            )
        if getattr(cfg, "compilation_cache_dir", None):
            from tpuflow.core.hw import enable_compilation_cache

            enable_compilation_cache(cfg.compilation_cache_dir)
        if self.state is None:
            b = train_ds
            self.init_state((b.img_height, b.img_width, 3))
        if self._train_step is None:
            self._make_steps()
        self.lr_controller = LRController(
            cfg.learning_rate,
            world_size=self.world,
            scale_by_world_size=cfg.scale_lr_by_world_size,
            warmup_epochs=cfg.warmup_epochs,
            steps_per_epoch=steps_per_epoch,
            decay=cfg.lr_decay,
            total_steps=epochs * steps_per_epoch,
            min_lr=cfg.min_lr,
        )
        # resume any checkpointed/prior plateau reduction (never restart
        # a resumed run at the full schedule LR)
        self.lr_controller.plateau_factor = float(
            jax.device_get(self.state.plateau_factor)
        )
        history = History()
        cbs = [history] + list(callbacks or [])
        cbs += self._callbacks_from_config(cbs)
        for cb in cbs:
            cb.set_trainer(self)
        self.stop_training = False
        for cb in cbs:
            cb.on_train_begin()
        # metrics/health plane (ISSUE 5): exporter + watchdogs; None
        # when disarmed (one `is not None` check per step then)
        from tpuflow.obs.health import monitor_from_config

        self.health = monitor_from_config(cfg)
        # fault-tolerance plane (ISSUE 10): cfg.recovery turns a
        # watchdog trip into rollback-to-last-good-checkpoint with the
        # bounded escalation ladder (tpuflow.train.recovery). The image
        # trainer's feed is a forward-only stream, so the replay is
        # BEST-EFFORT: state rolls back exactly, the stream continues
        # from where it is (exact-replay parity is the LM trainer's
        # contract — its epoch order is deterministic and seekable).
        # The skip-batch escalation level is likewise LM-only.
        from tpuflow.testing import faults
        from tpuflow.train.recovery import (policy_from_config,
                                            record_recovery)

        policy = policy_from_config(cfg)
        if policy is not None and self.health is None:
            raise ValueError(
                "cfg.recovery has no trip source: arm watchdog=True "
                "(or stall_timeout_s) so there is something to "
                "recover from"
            )
        self._recovery_policy = policy  # introspection (tests, bench)

        # preemption-safe mode (cfg.checkpoint_on_preempt): SIGTERM
        # sets a flag; the step loop finishes the CURRENT step, writes
        # a step-granular checkpoint, and stops cleanly. Multi-process
        # gangs agree collectively (any-host OR) every
        # preempt_sync_every steps so every process stops at the SAME
        # step; handler install/restore and the stop decision live in
        # train/preempt.py, shared with LMTrainer.
        from tpuflow.train.preempt import (should_stop,
                                           sigterm_preempt_flag)

        use_preempt = bool(
            self.cfg.checkpoint_on_preempt and self.cfg.checkpoint_dir
        )
        preempt_mp = jax.process_count() > 1
        sync_every = int(getattr(self.cfg, "preempt_sync_every", 16))

        # exact mid-epoch resume (maybe_resume with steps_per_epoch):
        # fast-forward the stream to the checkpointed position — the
        # discarded batches replay the interrupted epoch's prefix
        skip_steps = int(getattr(self, "_resume_skip_steps", 0) or 0)
        self._resume_skip_steps = 0
        if skip_steps:
            # the stashed position is only meaningful for the topology
            # maybe_resume was told about — a mismatched
            # steps_per_epoch or an explicit initial_epoch override
            # would apply the skip to the wrong stream position
            if skip_steps >= steps_per_epoch:
                raise ValueError(
                    f"resume position (+{skip_steps} steps) does not "
                    f"fit steps_per_epoch={steps_per_epoch}: "
                    "maybe_resume was given a different "
                    "steps_per_epoch — call fit with the same batch "
                    "size and data"
                )
            resumed_epoch = getattr(self, "_resume_epoch", None)
            if resumed_epoch is not None and initial_epoch != resumed_epoch:
                raise ValueError(
                    f"initial_epoch={initial_epoch} overrides the "
                    f"resumed mid-epoch position (epoch "
                    f"{resumed_epoch} +{skip_steps} steps) — pass "
                    "initial_epoch=maybe_resume(...) or drop it"
                )

        # fast-forward on the RAW host iterator — skipped batches must
        # never pay the H2D transfer _prefetch's _put would issue
        raw_iter = iter(train_ds)
        exhausted = False
        for _ in range(skip_steps):
            try:
                next(raw_iter)
            except StopIteration:
                exhausted = True
                break
        K = max(1, int(getattr(cfg, "superstep", 1)))
        depth = self._staging_depth(train_ds)
        # K=1 keeps the classic per-step dispatch loop (exact legacy
        # behavior); K>1 pulls RAW host batches and stages stacked
        # blocks for the fused scan instead
        train_iter = None if K > 1 else self._prefetch(raw_iter, depth)
        global_step = initial_epoch * steps_per_epoch + skip_steps
        lr = self.lr_controller.lr_for_step(global_step)
        from tpuflow.ckpt.checkpoint import join_async_writes

        from tpuflow.obs.health import closing as _closing_monitor

        preempted = False
        # epoch cursor is a while loop (ISSUE 10): a recovery rollback
        # re-enters an earlier epoch number with restored state (the
        # stream itself only moves forward — best-effort, see above)
        epoch = initial_epoch
        pending_skip = skip_steps  # consumed by the first epoch only
        rollback_anchor = global_step
        with sigterm_preempt_flag(use_preempt) as preempt, \
                join_async_writes(lambda: [
                    getattr(cb, "_async", None) for cb in cbs]), \
                _closing_monitor(self.health):
            while epoch < epochs:
                # explicit begin/end (not `with`): the body exits
                # through several break paths; trace.end is idempotent
                # so every path may close it
                ep_span = trace.begin("train.epoch", epoch=epoch)
                if self.health is not None:
                    # stepping resumes: the stall clock re-anchors
                    self.health.resume()
                step_metrics = []
                steps_this_epoch = steps_per_epoch - pending_skip
                pending_skip = 0
                if K > 1:
                    # superstep mode: one fused scan dispatch per block;
                    # blocks are chunked so every preempt-sync boundary
                    # falls on a block edge (cadence preserved)
                    sizes = self._superstep_sizes(
                        steps_this_epoch, K, global_step,
                        sync_every if (use_preempt and preempt_mp) else 0,
                    )
                    blocks = self._stage_superstep(raw_iter, sizes, depth)
                    for want in sizes:
                        if use_preempt and should_stop(
                                preempt, global_step, sync_every,
                                preempt_mp):
                            preempted = True
                            break
                        if (self.health is not None
                                and self.health.tripped):
                            break
                        blk = next(blocks, None)
                        if blk is None:
                            exhausted = True
                            break
                        k, images, labels = blk
                        for j in range(k):
                            faults.fire("train.step",
                                        step=global_step + j)
                        lrs = [
                            self.lr_controller.lr_for_step(global_step + j)
                            for j in range(k)
                        ]
                        if policy is not None and policy.lr_scale != 1.0:
                            lrs = [v * policy.lr_scale for v in lrs]
                        lr = lrs[-1]
                        with trace.span("train.superstep",
                                        phase="dispatch", k=k):
                            self.state, m = self._superstep(
                                self.state, images, labels,
                                jnp.asarray(lrs, jnp.float32),
                            )
                        m = faults.mutate_metrics(
                            "train.metrics", m,
                            step=global_step + k - 1, k=k)
                        # m holds (k,)-stacked per-step metrics, still
                        # device-resident — the epoch-end _mean_metrics
                        # fetch is the only host sync (the health
                        # monitor's fetch rides its own worker thread)
                        step_metrics.append(m)
                        if self.health is not None:
                            self.health.watch_device(
                                global_step + k - 1, m
                            )
                        global_step += k
                        for cb in cbs:
                            cb.on_superstep_end(global_step, m)
                        if k < want:
                            exhausted = True
                            break
                else:
                    for _ in range(steps_this_epoch):
                        if use_preempt and should_stop(
                                preempt, global_step, sync_every,
                                preempt_mp):
                            preempted = True
                            break
                        if (self.health is not None
                                and self.health.tripped):
                            break
                        lr = self.lr_controller.lr_for_step(global_step)
                        if policy is not None:
                            lr *= policy.lr_scale  # escalation drop
                        try:
                            images, labels = next(train_iter)
                        except StopIteration:
                            # finite (non-infinite) stream ran dry: end
                            # training cleanly after this partial epoch
                            # (Keras semantics)
                            exhausted = True
                            break
                        faults.fire("train.step", step=global_step)
                        with trace.span("train.dispatch",
                                        phase="dispatch"):
                            self.state, m = self._train_step(
                                self.state, images, labels,
                                jnp.asarray(lr, jnp.float32),
                            )
                        m = faults.mutate_metrics("train.metrics", m,
                                                  step=global_step)
                        step_metrics.append(m)
                        if self.health is not None:
                            self.health.watch_device(global_step, m)
                        global_step += 1
                if preempted:
                    from tpuflow.ckpt import save_step_checkpoint

                    with trace.span("train.checkpoint",
                                    phase="checkpoint"):
                        path = save_step_checkpoint(
                            self.cfg.checkpoint_dir, self.state,
                            global_step
                        )
                    history.history.setdefault("preempted_at_step", []
                                               ).append(global_step)
                    if verbose:
                        print(f"preempted at step {global_step}; "
                              f"saved {path}")
                    trace.end(ep_span, preempted=True)
                    break
                if exhausted and not step_metrics:
                    trace.end(ep_span, exhausted=True)
                    break
                if self.health is not None:
                    # step loop over: pause the stall watch (epoch-end
                    # eval/checkpoint may legitimately exceed the
                    # timeout), then settle the async guard — a trip
                    # in this epoch stops the run now (training past a
                    # NaN only burns chip-hours)
                    self.health.pause()
                    self.health.drain()
                    if self.health.tripped:
                        trips = self.health.trips()
                        tstep = int(next(
                            (t["step"] for t in trips
                             if "step" in t), global_step
                        ))
                        reason = (trips[0].get("reason",
                                               "watchdog trip")
                                  if trips else "watchdog trip")
                        act = (policy.on_trip(tstep, reason=reason)
                               if policy is not None else None)
                        if act is not None and act.kind == "rollback":
                            # auto-recovery (ISSUE 10): roll state back
                            # to the last VALID checkpoint and keep
                            # training (stream continues forward —
                            # best-effort, see fit docstring); nothing
                            # on disk yet ⇒ restart from a fresh init
                            if act.backoff_s > 0:
                                import time as _time

                                _time.sleep(act.backoff_s)
                            from tpuflow.ckpt.checkpoint import (
                                latest_resume_point, restore_into_state)

                            found = (latest_resume_point(
                                self.cfg.checkpoint_dir,
                                steps_per_epoch)
                                if self.cfg.checkpoint_dir else None)
                            if found is not None:
                                rpath, r_epoch, r_skip = found
                                with trace.span("train.rollback",
                                                phase="checkpoint"):
                                    self.state = restore_into_state(
                                        rpath, self.state)
                            else:
                                rpath, r_epoch, r_skip = None, 0, 0
                                self.init_state((train_ds.img_height,
                                                 train_ds.img_width, 3))
                            self._tag_state()
                            rollback_to = (r_epoch * steps_per_epoch
                                           + r_skip)
                            if int(self.state.step) != rollback_to:
                                # weights-only checkpoint: the restore's
                                # {params, batch_stats} branch kept the
                                # POISONED step/opt_state — a NaN'd
                                # Adam moment would re-NaN every
                                # replay, so re-init the optimizer
                                # fresh at the rollback point
                                # (params-only recovery)
                                self.state = self.state.replace(
                                    step=rollback_to,
                                    opt_state=self.tx.init(
                                        self.state.params),
                                )
                            record_recovery(
                                policy, rollback_from=global_step,
                                rollback_to=rollback_to)
                            self.health.acknowledge()
                            history.history.setdefault(
                                "recovered_at_step", []
                            ).append(float(tstep))
                            if verbose:
                                print(
                                    f"watchdog tripped ({reason}); "
                                    f"rollback #{act.retry} to step "
                                    f"{rollback_to} "
                                    + (f"[{rpath}]" if rpath
                                       else "[re-init]")
                                )
                            global_step = rollback_to
                            epoch = r_epoch
                            # a mid-epoch step checkpoint restores at
                            # r_skip steps INTO epoch r_epoch: the
                            # re-entered epoch must run the remainder,
                            # or global_step drifts off the epoch grid
                            # (LR schedule, future checkpoints, resume
                            # math all key on it)
                            pending_skip = r_skip
                            rollback_anchor = rollback_to
                            trace.end(ep_span, rollback=True)
                            continue
                        history.history.setdefault(
                            "watchdog_tripped_at", []
                        ).append(float(tstep))
                        if verbose:
                            why = (act.reason if act is not None
                                   else reason)
                            print(f"watchdog tripped: {why}; "
                                  f"stopping at step {global_step}")
                        trace.end(ep_span, watchdog_tripped=True)
                        break
                with trace.span("train.metrics_fetch", phase="device"):
                    logs = _mean_metrics(step_metrics)
                logs["lr"] = lr
                # re-tag the (donation-replaced) state at the epoch
                # boundary so the ledger's params/opt_state stay honest
                self._tag_state()
                if val_ds is not None:
                    val_logs = self.evaluate(val_ds, steps=validation_steps)
                    logs.update({f"val_{k}": v for k, v in val_logs.items()})
                if verbose:
                    print(f"epoch {epoch}: " + " ".join(
                        f"{k}={v:.4f}" for k, v in logs.items()))
                for cb in cbs:
                    cb.on_epoch_end(epoch, logs)
                if policy is not None:
                    # clean steps since the last rollback: past the
                    # reset threshold the escalation ladder clears
                    policy.note_progress(global_step - rollback_anchor)
                trace.end(ep_span)
                epoch += 1
                if self.stop_training or exhausted:
                    break
        # the closing() cm above stopped the stall thread (exception
        # paths included); trip state stays readable on self.health
        for cb in cbs:
            cb.on_train_end()
        return history

    def _callbacks_from_config(self, existing: List[Callback]) -> List[Callback]:
        """Wire TrainConfig's callback fields (plateau/early-stop/
        checkpoint) unless the caller already supplied that callback
        type — config must not be silently dead."""
        from tpuflow.train.callbacks import (
            EarlyStopping,
            ModelCheckpoint,
            ReduceLROnPlateau,
        )

        have = {type(cb) for cb in existing}
        cfg = self.cfg
        out: List[Callback] = []
        if cfg.reduce_on_plateau_patience and ReduceLROnPlateau not in have:
            out.append(
                ReduceLROnPlateau(
                    patience=cfg.reduce_on_plateau_patience,
                    factor=cfg.reduce_on_plateau_factor,
                )
            )
        if cfg.early_stopping_patience and EarlyStopping not in have:
            out.append(EarlyStopping(patience=cfg.early_stopping_patience))
        if cfg.checkpoint_dir and ModelCheckpoint not in have:
            out.append(ModelCheckpoint(
                cfg.checkpoint_dir,
                async_write=getattr(cfg, 'async_checkpoint', False),
            ))
        if cfg.consistency_check_every > 0:
            from tpuflow.train.callbacks import ReplicaConsistencyCheck

            if ReplicaConsistencyCheck not in have:
                out.append(
                    ReplicaConsistencyCheck(cfg.consistency_check_every)
                )
        return out

    def evaluate(self, ds, steps: Optional[int] = None) -> Dict[str, float]:
        """Eval with cross-replica metric averaging (≙ MetricAverageCallback)."""
        if self._eval_step is None:
            self._make_steps()
        steps = steps or ds.steps_per_epoch()
        it = self._prefetch(iter(ds), self._staging_depth(ds),
                            component="eval")
        ms = []
        with trace.span("train.eval", steps=steps):
            for _ in range(steps):
                images, labels = next(it)
                ms.append(self._eval_step(self.state, images, labels))
            return _mean_metrics(ms)

    def predict_logits(self, images: np.ndarray) -> np.ndarray:
        """Forward pass on a host batch (single-process convenience)."""
        if self._eval_step is None:
            self._make_steps()
        x = preprocess_input(jnp.asarray(images), dtype=getattr(self.model, "dtype", jnp.bfloat16))
        logits = self.model.apply(
            {"params": self.state.params, "batch_stats": self.state.batch_stats},
            x,
            train=False,
        )
        return np.asarray(logits, dtype=np.float32)


def _mean_metrics(ms: List[Dict[str, jax.Array]]) -> Dict[str, float]:
    """Per-step mean over a mixed list of scalar metric dicts (the
    per-step loop) and (k,)-stacked superstep blocks — every STEP
    weighs equally either way."""
    out: Dict[str, float] = {}
    if not ms:
        return out
    host = jax.device_get(ms)
    for k in host[0]:
        out[k] = float(np.mean(
            np.concatenate([np.atleast_1d(m[k]) for m in host])
        ))
    return out
