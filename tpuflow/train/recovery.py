"""Auto-recovery + elastic-resize policy (ISSUE 10 tentpole pieces 2-3).

PR 5's watchdogs DETECT a bad run (NaN, loss spike, stall) and halt it
with a post-mortem; at fleet scale the halt itself is the cost — every
trip that a rollback would have absorbed becomes a human page plus the
queue time of a manual restart. This module is the decision layer that
makes the watchdogs load-bearing:

- :class:`RecoveryPolicy` — turns a watchdog trip into a bounded,
  escalating response: rollback to the last good checkpoint and replay
  (transient faults: a cosmic-ray NaN, a bad host read); after
  ``lr_drop_after`` consecutive trips also drop the LR by
  ``lr_drop_factor`` (instability: the large-batch divergence regime
  of Goyal et al., PAPERS.md — the same knob ReduceLROnPlateau turns,
  pulled by the trip instead of a plateau); after
  ``skip_batch_after`` consecutive trips also SKIP the poisoned
  step's batch on replay (data faults: one toxic batch deterministically
  NaNs every replay — dropping it is the only forward path); past
  ``max_retries`` consecutive trips, halt with the classic post-mortem
  (a policy that never gives up turns a hard bug into an infinite
  chip-hour burn). Progress resets the ladder: a rollback that then
  trains ``progress_reset_steps`` clean steps was a recovery, not a
  loop.

- :class:`ElasticController` — the resize decision for replica
  loss/join. ``check(now_world)`` returns the new desired
  data-parallel world (or None); the trainers poll it ONLY at
  superstep block boundaries (PR 2's clean resize points — no
  in-flight collective to tear). The LR rescale follows Goyal et al.'s
  linear rule via :func:`goyal_lr_scale` — the LRController already
  scales by world size, so a resized fit rebuilds it with the new
  world and the schedule follows.

The policies are pure host state machines (injectable, unit-testable);
the trainers wire them in ``fit`` (tpuflow/train/lm.py, trainer.py).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional


def goyal_lr_scale(old_world: int, new_world: int) -> float:
    """Linear LR scaling across a data-parallel resize (Goyal et al.,
    *Accurate, Large Minibatch SGD*): LR ∝ number of replicas, so a
    resize from W→W' multiplies the LR by W'/W. The trainers get this
    for free by rebuilding the LRController with the new world size
    when ``scale_lr_by_world_size`` is on; this helper is the explicit
    form (used when scaling is off, and by tests pinning the rule)."""
    if old_world < 1 or new_world < 1:
        raise ValueError(
            f"world sizes must be >= 1, got {old_world} -> {new_world}"
        )
    return float(new_world) / float(old_world)


@dataclasses.dataclass
class RecoveryAction:
    """One trip's verdict. ``kind`` is ``'rollback'`` or ``'halt'``;
    on rollback, ``lr_scale`` multiplies the run's LR (cumulative
    across the ladder, 1.0 = no drop), ``skip_step`` names a global
    step whose batch the replay must drop (None = replay everything),
    ``backoff_s`` is the pre-restore sleep."""

    kind: str
    retry: int = 0
    lr_scale: float = 1.0
    skip_step: Optional[int] = None
    backoff_s: float = 0.0
    reason: str = ""


class RecoveryPolicy:
    """Bounded-retry escalation ladder over watchdog trips.

    Consecutive-failure accounting: ``on_trip`` increments the retry
    count; ``note_progress(steps)`` resets it once a post-rollback run
    survives ``progress_reset_steps`` steps — so a month-long run may
    absorb many ISOLATED faults while a tight trip loop still halts
    after ``max_retries``.
    """

    def __init__(
        self,
        max_retries: int = 3,
        backoff_s: float = 0.0,
        backoff_mult: float = 2.0,
        lr_drop_after: int = 2,
        lr_drop_factor: float = 0.5,
        skip_batch_after: int = 3,
        progress_reset_steps: int = 64,
    ):
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {max_retries}")
        if not 0.0 < lr_drop_factor <= 1.0:
            raise ValueError(
                f"lr_drop_factor must be in (0, 1], got {lr_drop_factor}"
            )
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.backoff_mult = float(backoff_mult)
        self.lr_drop_after = int(lr_drop_after)
        self.lr_drop_factor = float(lr_drop_factor)
        self.skip_batch_after = int(skip_batch_after)
        self.progress_reset_steps = int(progress_reset_steps)
        self.retries = 0          # consecutive trips since progress
        self.lr_scale = 1.0       # cumulative drop applied so far
        self.history: List[Dict[str, Any]] = []  # flight-note feed

    def on_trip(self, tripped_step: int,
                reason: str = "watchdog trip") -> RecoveryAction:
        """The decision for one trip at ``tripped_step``."""
        self.retries += 1
        if self.retries > self.max_retries:
            act = RecoveryAction(
                kind="halt", retry=self.retries,
                lr_scale=self.lr_scale,
                reason=f"{reason}: retry budget exhausted "
                       f"({self.max_retries})",
            )
        else:
            if self.retries >= self.lr_drop_after:
                self.lr_scale *= self.lr_drop_factor
            act = RecoveryAction(
                kind="rollback",
                retry=self.retries,
                lr_scale=self.lr_scale,
                skip_step=(
                    tripped_step
                    if self.retries >= self.skip_batch_after else None
                ),
                backoff_s=self.backoff_s
                * (self.backoff_mult ** (self.retries - 1)),
                reason=reason,
            )
        self.history.append({
            "step": int(tripped_step),
            "retry": self.retries,
            "action": act.kind,
            "lr_scale": act.lr_scale,
            "skip_step": act.skip_step,
            "reason": reason,
            "ts": time.time(),
        })
        return act

    def note_progress(self, steps_since_rollback: int) -> None:
        """Training survived ``steps_since_rollback`` steps after the
        last rollback: once past the reset threshold the ladder state
        clears (the NEXT fault starts at retry 1 with the full LR —
        the drop was an escalation device, not a permanent schedule
        change; a genuinely unstable run re-earns it in two trips)."""
        if (self.retries and
                steps_since_rollback >= self.progress_reset_steps):
            self.retries = 0
            self.lr_scale = 1.0


def policy_from_config(cfg) -> Optional[RecoveryPolicy]:
    """The trainers' one-liner: a :class:`RecoveryPolicy` from
    ``TrainConfig``'s recovery fields, or None when disarmed
    (``cfg.recovery`` false)."""
    if not getattr(cfg, "recovery", False):
        return None
    return RecoveryPolicy(
        max_retries=getattr(cfg, "recovery_max_retries", 3),
        backoff_s=getattr(cfg, "recovery_backoff_s", 0.0),
        lr_drop_after=getattr(cfg, "recovery_lr_drop_after", 2),
        lr_drop_factor=getattr(cfg, "recovery_lr_drop_factor", 0.5),
        skip_batch_after=getattr(cfg, "recovery_skip_batch_after", 3),
    )


def record_recovery(policy: RecoveryPolicy, *, rollback_from: int,
                    rollback_to: int, kind: str = "rollback") -> None:
    """Publish one recovery event to the observability plane:
    ``train.recoveries_total`` / ``train.rollback_steps_total``
    counters (Prometheus + /v1/metrics for free via the registry) and
    a ``recovery`` note on every future flight-record manifest — the
    post-mortem of a run that recovered five times must SHOW the five
    recoveries (ISSUE 10 satellite)."""
    from tpuflow.obs import flight
    from tpuflow.obs.gauges import inc_counter

    inc_counter("train.recoveries_total")
    inc_counter("train.rollback_steps_total",
                max(0, int(rollback_from) - int(rollback_to)))
    flight.annotate("recovery", list(policy.history))


class ElasticController:
    """Desired-world oracle for elastic data-parallel resize.

    ``desired`` is a zero-arg callable returning the CURRENT desired
    number of data-parallel replicas (a cluster-manager hook, a
    membership file's line count, a test's scripted schedule...).
    :meth:`check` compares it against the running world and returns
    the agreed new world when they differ — at most once per
    ``min_interval_s`` so a flapping oracle cannot thrash recompiles.

    Multi-process gangs must AGREE on the resize step (the same
    identical-collective-schedule invariant the preemption flag
    honors): ``check`` routes the desired value through
    :func:`tpuflow.train.preempt.agree_on_world` — an all-process MIN
    — when ``multiprocess`` is set, so every process resizes at the
    same block boundary or none does."""

    def __init__(self, desired: Callable[[], int],
                 min_interval_s: float = 0.0,
                 multiprocess: Optional[bool] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.desired = desired
        self.min_interval_s = float(min_interval_s)
        self.clock = clock
        if multiprocess is None:
            import jax

            multiprocess = jax.process_count() > 1
        self.multiprocess = bool(multiprocess)
        self._last_check = -float("inf")
        self._refused: Optional[int] = None
        self.resizes: List[Dict[str, Any]] = []

    def check(self, current_world: int) -> Optional[int]:
        """The agreed new world size, or None (no change / throttled).
        Call ONLY at superstep block boundaries — a resize tears down
        the compiled step."""
        now = self.clock()
        if self.multiprocess:
            # the agreement collective must run on EVERY process at
            # EVERY boundary (the identical-collective-schedule
            # invariant): a per-host wall-clock throttle deciding
            # whether to ENTER the allgather would let one process
            # skip it while another blocks in it forever. Instead the
            # throttle verdict itself is merged through the collective
            # — a throttled process contributes 0, the MIN makes the
            # whole gang stand down together.
            from tpuflow.train.preempt import agree_on_world

            ready = now - self._last_check >= self.min_interval_s
            want = agree_on_world(
                int(self.desired()) if ready else 0)
            if want < 1:
                return None
            self._last_check = now
        else:
            if now - self._last_check < self.min_interval_s:
                return None
            self._last_check = now
            want = int(self.desired())
        if self._refused is not None:
            # a refused target stays suppressed until the oracle asks
            # for something else — the refusal came from an invariant
            # (batch divisibility) that re-asking cannot change, and a
            # zero-interval controller would otherwise re-ask at every
            # boundary and starve training
            if want == self._refused:
                return None
            self._refused = None
        if want < 1 or want == int(current_world):
            return None
        return want

    def refuse(self, world: int) -> None:
        """The trainer could not honor a resize to ``world`` (e.g. the
        global batch is not divisible by it): suppress that target
        until :attr:`desired` changes its answer."""
        self._refused = int(world)

    def note_resize(self, old_world: int, new_world: int,
                    global_step: int) -> None:
        """Publish one resize to the plane (counter + flight note) and
        remember it for tests/introspection."""
        from tpuflow.obs import flight
        from tpuflow.obs.gauges import inc_counter, set_gauge

        rec = {
            "step": int(global_step),
            "from_world": int(old_world),
            "to_world": int(new_world),
            "lr_scale": goyal_lr_scale(old_world, new_world),
            "ts": time.time(),
        }
        self.resizes.append(rec)
        inc_counter("train.elastic_resizes_total")
        set_gauge("train.world_size", float(new_world))
        flight.annotate("elastic_resize", list(self.resizes))
