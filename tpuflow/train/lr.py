"""Learning-rate control: world-size scaling, warmup, plateau factor.

≙ the reference's three LR mechanisms (P1/03_model_training_distributed.py):
- base LR × world size (:300-302, the Goyal et al. linear-scaling rule),
- ``LearningRateWarmupCallback(warmup_epochs=5)`` ramping from the base
  LR to the scaled LR over the first epochs (:315-318),
- ``ReduceLROnPlateau(patience=10)`` (:319-322).

Here all three compose in one host-side controller producing the LR for
every step; the value enters the jitted step as a traced scalar so
adjustments never recompile (per-BATCH warmup granularity, same as the
Horovod callback).

Beyond-reference knob: ``decay='cosine'`` anneals the post-warmup LR
to ``min_lr`` over ``total_steps`` (the standard warmup+cosine LM
recipe); it composes multiplicatively with the plateau factor, and the
reference-parity default stays the constant schedule.
"""

from __future__ import annotations

import math


class LRController:
    def __init__(
        self,
        base_lr: float,
        world_size: int = 1,
        scale_by_world_size: bool = True,
        warmup_epochs: int = 5,
        steps_per_epoch: int = 1,
        decay: str = "none",
        total_steps: int = 0,
        min_lr: float = 0.0,
    ):
        if decay not in ("none", "cosine"):
            raise ValueError(f"decay must be 'none' or 'cosine', got {decay!r}")
        self.base_lr = float(base_lr)
        self.target_lr = float(base_lr) * (world_size if scale_by_world_size else 1)
        self.warmup_steps = max(0, int(warmup_epochs) * int(steps_per_epoch))
        if decay == "cosine" and total_steps <= 0:
            # an unset/zero horizon is a programming error (the anneal
            # has no endpoint), not a config-knob combination — fail
            raise ValueError(
                f"decay='cosine' requires total_steps > 0, got "
                f"{total_steps}"
            )
        if decay == "cosine" and total_steps <= self.warmup_steps:
            # e.g. the default warmup_epochs=5 on a 3-epoch run: a hard
            # error here would fail a config-knob combination at fit()
            # time, after data prep. Clamp warmup to HALF the run so a
            # real anneal window remains (total_steps - 1 would leave
            # the anneal's p=0 point as the final step — peak LR on
            # every executed step, decay='none' in effect)
            import warnings

            clamped = int(total_steps) // 2
            warnings.warn(
                f"decay='cosine' with warmup steps ({self.warmup_steps}) "
                f">= total_steps ({total_steps}): clamping warmup to "
                f"{clamped} steps so the anneal runs over the second "
                "half of the run",
                stacklevel=2,
            )
            self.warmup_steps = clamped
        self.plateau_factor = 1.0
        self.decay = decay
        self.total_steps = int(total_steps)
        self.min_lr = float(min_lr)

    def lr_for_step(self, global_step: int) -> float:
        if self.warmup_steps > 0 and global_step < self.warmup_steps:
            frac = global_step / self.warmup_steps
            lr = self.base_lr + (self.target_lr - self.base_lr) * frac
        elif self.decay == "cosine" and self.total_steps > self.warmup_steps:
            p = (global_step - self.warmup_steps) / (
                self.total_steps - self.warmup_steps
            )
            p = min(max(p, 0.0), 1.0)
            lr = self.min_lr + (self.target_lr - self.min_lr) * 0.5 * (
                1.0 + math.cos(math.pi * p)
            )
        else:
            lr = self.target_lr
        return max(lr * self.plateau_factor, self.min_lr)

    def reduce(self, factor: float) -> float:
        """Apply a plateau reduction; returns the new PEAK LR
        (``target_lr x plateau_factor``) — under ``decay='cosine'`` the
        actual per-step LR additionally follows the anneal curve and
        the ``min_lr`` floor (:meth:`lr_for_step`)."""
        self.plateau_factor *= factor
        return self.target_lr * self.plateau_factor
