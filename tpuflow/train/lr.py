"""Learning-rate control: world-size scaling, warmup, plateau factor.

≙ the reference's three LR mechanisms (P1/03_model_training_distributed.py):
- base LR × world size (:300-302, the Goyal et al. linear-scaling rule),
- ``LearningRateWarmupCallback(warmup_epochs=5)`` ramping from the base
  LR to the scaled LR over the first epochs (:315-318),
- ``ReduceLROnPlateau(patience=10)`` (:319-322).

Here all three compose in one host-side controller producing the LR for
every step; the value enters the jitted step as a traced scalar so
adjustments never recompile (per-BATCH warmup granularity, same as the
Horovod callback).
"""

from __future__ import annotations


class LRController:
    def __init__(
        self,
        base_lr: float,
        world_size: int = 1,
        scale_by_world_size: bool = True,
        warmup_epochs: int = 5,
        steps_per_epoch: int = 1,
    ):
        self.base_lr = float(base_lr)
        self.target_lr = float(base_lr) * (world_size if scale_by_world_size else 1)
        self.warmup_steps = max(0, int(warmup_epochs) * int(steps_per_epoch))
        self.plateau_factor = 1.0
        self.min_lr = 0.0

    def lr_for_step(self, global_step: int) -> float:
        if self.warmup_steps > 0 and global_step < self.warmup_steps:
            frac = global_step / self.warmup_steps
            lr = self.base_lr + (self.target_lr - self.base_lr) * frac
        else:
            lr = self.target_lr
        return max(lr * self.plateau_factor, self.min_lr)

    def reduce(self, factor: float) -> float:
        """Apply a plateau reduction; returns the new post-warmup LR."""
        self.plateau_factor *= factor
        return self.target_lr * self.plateau_factor
