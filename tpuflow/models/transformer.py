"""Decoder-only transformer language model — the long-context family.

The reference has no text models and no attention at all (SURVEY.md
§2c, §5.7); this is the capability the TPU build adds as first-class:
a causal LM whose design axes map one-to-one onto the mesh:

- **Tensor parallelism**: Megatron-style — q/k/v and the MLP's
  gate/up projections column-sharded over the ``model`` axis, output
  projections row-sharded (one all-reduce per block under GSPMD); the
  token embedding and LM head are vocab-sharded. Same
  ``nn.with_partitioning`` idiom as the ViT family
  (tpuflow.models.vit), auto-lowered by jit over a (data, model) mesh.
- **Sequence parallelism**: ``seq_axis="seq"`` switches to manual mode
  for use inside ``shard_map`` with TOKENS sharded along the sequence:
  attention becomes causal ring attention (K/V shards rotating over
  ICI — tpuflow.parallel.ring_attention), rotary positions are offset
  by the shard's global start, and everything else is per-token.
- **Attention impls**: ``attn_impl='flash'`` forces the Pallas
  blockwise kernel (tpuflow.ops.attention) with causal block skipping;
  ``'einsum'`` forces XLA einsums (fully GSPMD-partitionable);
  ``'auto'`` (default) resolves per sequence length via
  tpuflow.ops.pick_attn_impl — einsum below 1024 tokens, flash on TPU
  at 1024+ where avoiding the materialized O(S²) score matrix pays.

Pre-norm blocks with RMSNorm, SwiGLU MLP, rotary position embeddings,
no biases — the standard modern decoder recipe, chosen because every
op in it is shard-uniform (SP needs no per-position parameters).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from tpuflow.core.compat import axis_size as _axis_size
from tpuflow.ops.attention import flash_attention, mha_xla, pick_attn_impl
from tpuflow.parallel.mesh import MODEL_AXIS
from tpuflow.parallel.ring_attention import ring_attention


from tpuflow.models._layers import dense_init as _dense_init  # noqa: E402
from tpuflow.models._layers import part as _part  # noqa: E402


class RMSNorm(nn.Module):
    dtype: Any = jnp.bfloat16
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        x32 = x.astype(jnp.float32)
        scale = self.param("scale", nn.initializers.ones_init(),
                           (x.shape[-1],), jnp.float32)
        y = x32 * lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True)
                            + self.eps)
        return (y * scale).astype(self.dtype)


def rotary_embed(q, k, positions, theta: float = 10000.0,
                 scaling: float = 1.0, scaling_kind: str = "linear"):
    """Apply rotary position embeddings to q, k of shape (B, H, S, D).

    ``positions``: (S,) int32 GLOBAL token positions — under sequence
    parallelism the caller passes the shard's absolute positions so
    rotations agree across shards — or (B, S) PER-ROW positions
    (sequence packing: each packed document restarts at 0). Computed
    in float32.

    ``scaling`` — the RoPE context-extension factor; identity at 1.0.
    ``scaling_kind`` selects the interpolation:

    - ``'linear'`` (Chen et al. 2023 position interpolation): positions
      divide by the factor before the rotation — rotations at position
      s·p under scaling s equal rotations at p unscaled. Uniformly
      compresses ALL frequencies (the high-frequency/local detail
      channels included), so a brief fine-tune at the new length is
      the standard companion.
    - ``'ntk'`` (NTK-aware, fixed): the base theta is raised to
      ``theta · s^(d/(d-2))`` instead — low frequencies stretch to
      cover the longer context while the highest frequency is left
      (asymptotically) untouched, which tends to preserve local
      attention patterns better WITHOUT fine-tuning.
    """
    d = q.shape[-1]
    half = d // 2
    if scaling_kind not in ("linear", "ntk"):
        raise ValueError(
            f"scaling_kind must be 'linear' or 'ntk', got {scaling_kind!r}"
        )
    pos = positions.astype(jnp.float32)
    if scaling != 1.0 and scaling_kind == "ntk":
        theta = theta * scaling ** (d / max(1, d - 2))
    inv_freq = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    if scaling != 1.0 and scaling_kind == "linear":
        pos = pos / scaling
    angles = pos[..., None] * inv_freq  # (..., S, half)
    if angles.ndim == 2:  # (S, half): shared across batch and heads
        cos = jnp.cos(angles)[None, None, :, :]
        sin = jnp.sin(angles)[None, None, :, :]
    else:  # (B, S, half): per-row packed positions, shared across heads
        cos = jnp.cos(angles)[:, None, :, :]
        sin = jnp.sin(angles)[:, None, :, :]

    def rot(t):
        t32 = t.astype(jnp.float32)
        t1, t2 = t32[..., :half], t32[..., half:]
        out = jnp.concatenate(
            [t1 * cos - t2 * sin, t1 * sin + t2 * cos], axis=-1
        )
        return out.astype(t.dtype)

    return rot(q), rot(k)


class CausalAttention(nn.Module):
    dim: int
    heads: int
    dtype: Any
    attn_impl: str = "auto"  # auto | flash
    seq_axis: Optional[str] = None  # set → causal ring attention
    rope_theta: float = 10000.0
    decode: bool = False  # autoregressive KV-cache mode
    # sequence-shard layout under seq_axis: 'contiguous' (shard d holds
    # tokens [d*s,(d+1)*s)) or 'striped' (shard d holds d, d+n, ... —
    # balances the causal ring; the TRAINER permutes tokens/logits)
    sp_layout: str = "contiguous"
    attn_window: Optional[int] = None  # sliding-window (local) attention
    # grouped-query attention: kv_heads < heads shares each K/V head
    # across heads//kv_heads query heads (Llama-2/Mistral style) —
    # the KV cache and the K/V projections shrink by the group factor,
    # the decode step's dominant memory traffic. None = MHA.
    kv_heads: Optional[int] = None
    # batched-bh flash grid (ops.attention bh_block): (batch*heads)
    # rows per kernel grid cell — the short-sequence per-cell-overhead
    # amortizer. 1 = classic kernel; ignored by einsum/ring paths.
    attn_bh_block: int = 1
    # RoPE context-extension factor (1.0 = off) + interpolation kind
    # ('linear' position interpolation | 'ntk' theta scaling); applies
    # in training AND the KV-cache decode path.
    rope_scaling: float = 1.0
    rope_scaling_kind: str = "linear"
    # paged KV cache (decode only): kv_pages physical pages of
    # kv_page_size tokens each, shared by EVERY sequence in the
    # process — the cache collection holds (kv_pages, KVH, page_size,
    # head_dim) pools instead of per-row (B, KVH, max_len, head_dim)
    # buffers, and each call carries a per-row ``page_table``
    # indirection + ``write_pos``. KV memory then scales with tokens
    # that exist, not with rows × horizon (vLLM's PagedAttention idea;
    # tpuflow.serve.pages owns the allocator/prefix-sharing policy).
    # kv_quant='int8' stores pages as int8 with a per-page scale
    # vector (one f32 scale per token slot), dequantized in the read.
    kv_pages: Optional[int] = None
    kv_page_size: int = 16
    kv_quant: Optional[str] = None  # None | 'int8'
    # fused paged-attention decode kernel (ops.attention.
    # paged_flash_decode): the single-token decode step writes the new
    # K/V and reads through the page table INSIDE one Pallas call —
    # no dense (B, KVH, L, D) gather. None = auto (TPU backend; off-
    # TPU the portable scatter+gather path stays the bitwise-pinned
    # production path and the kernel runs only under interpret-mode
    # tests); True forces it (interpret off-TPU); False never. Multi-
    # token calls (join prefill, speculative verify) and int8 stores
    # always take the portable path.
    paged_kernel: Optional[bool] = None

    @nn.compact
    def __call__(self, x, segment_ids=None, positions_override=None,
                 pad_lens=None, page_table=None, write_pos=None,
                 write_mask=None):
        tp = self.seq_axis is None
        head_dim = self.dim // self.heads
        kvh = self.kv_heads or self.heads
        group = self.heads // kvh
        b, s, _ = x.shape
        if segment_ids is not None and (
                self.seq_axis is not None or self.decode):
            raise ValueError(
                "segment_ids is not supported with seq_axis (ring "
                "attention) or decode mode"
            )
        if pad_lens is not None and not self.decode:
            raise ValueError(
                "pad_lens (bucketed left-padding) is a decode-mode "
                "feature; training paths mask pads via segment_ids"
            )

        def proj_in(name, n_heads):
            return nn.Dense(
                n_heads * head_dim,
                use_bias=False,
                dtype=self.dtype,
                kernel_init=_part(_dense_init, (None, MODEL_AXIS), tp),
                name=name,
            )(x)

        def heads_first(t, n_heads):  # (B, S, C) → (B, H, S, D)
            return t.reshape(b, s, n_heads, head_dim).transpose(0, 2, 1, 3)

        q = heads_first(proj_in("query", self.heads), self.heads)
        k = heads_first(proj_in("key", kvh), kvh)
        v = heads_first(proj_in("value", kvh), kvh)

        def expand_kv(t):
            """(B, KVH, S, D) → (B, H, S, D): share each K/V head
            across its query-head group (no-op for MHA)."""
            if group == 1:
                return t
            return jnp.repeat(t, group, axis=1)

        paged = self.decode and self.kv_pages is not None
        if (page_table is not None or write_pos is not None) and not paged:
            raise ValueError(
                "page_table/write_pos require decode mode with kv_pages "
                "set (paged KV cache)"
            )
        if paged and pad_lens is not None:
            raise ValueError(
                "pad_lens (bucketed left-padding) does not combine with "
                "the paged KV cache — paged rows live at their logical "
                "positions (no pads)"
            )
        if paged and self.has_variable("cache", "key_rows"):
            # ---- rowwise dense-window decode (ISSUE 11) --------------
            # The hoisted-gather fast path: the SEGMENT executable
            # gathers each row's pages into a dense (B, KVH, L, D)
            # window ONCE per segment (infer.generate's hoisted
            # segment fn), the per-token steps run against that window
            # here — write via one-hot select at the row's own
            # position, read via the same masked einsum as the paged
            # path below — and the segment scatters written pages back
            # to the store ONCE at the end. Per-step cost is then the
            # contiguous path's (no per-step gather/scatter), with the
            # window length W*page_size chosen per segment (shorter
            # than the full horizon while rows are young). The caller
            # provides the window in the cache collection; page
            # variables are never touched on this path.
            if write_pos is None:
                raise ValueError(
                    "rowwise dense-window decode needs write_pos")
            if s != 1:
                raise ValueError(
                    "rowwise dense-window decode is the single-token "
                    "segment step (s=1); multi-token paged calls go "
                    "through the page table")
            kr = self.variable("cache", "key_rows", lambda: None)
            vr = self.variable("cache", "value_rows", lambda: None)
            L = kr.value.shape[2]
            pos = write_pos[:, None] + jnp.arange(s, dtype=jnp.int32)
            q, k = rotary_embed(q, k, pos, self.rope_theta,
                                self.rope_scaling,
                                self.rope_scaling_kind)
            wm = (jnp.ones((b, s), bool) if write_mask is None
                  else write_mask)
            # SCATTER the token into its window slot — O(B·KVH·D) and
            # in place on the scan carry. (A full-window one-hot
            # select here rewrites the whole dense window every step
            # and hands the hoisting win straight back.) Masked rows
            # read-modify-write their current slot content unchanged.
            bidx = jnp.arange(b)
            posc = jnp.clip(pos[:, 0], 0, L - 1)
            kt0 = k[:, :, 0, :]  # (B, KVH, D)
            vt0 = v[:, :, 0, :]
            wmc = wm[:, 0][:, None, None]
            cur_k = kr.value[bidx, :, posc, :]
            cur_v = vr.value[bidx, :, posc, :]
            kr.value = kr.value.at[bidx, :, posc, :].set(
                jnp.where(wmc, kt0.astype(kr.value.dtype), cur_k))
            vr.value = vr.value.at[bidx, :, posc, :].set(
                jnp.where(wmc, vt0.astype(vr.value.dtype), cur_v))
            key_pos = jnp.arange(L)
            ok = key_pos[None, None, :] <= pos[:, :, None]  # (B,s,L)
            if self.attn_window is not None:
                ok = ok & (key_pos[None, None, :]
                           > pos[:, :, None] - self.attn_window)
            mask = ok[:, None, None]  # (B,1,1,s,L)
            qg = q.reshape(b, kvh, group, s, head_dim)
            scores = jnp.einsum(
                "bkgqd,bksd->bkgqs",
                qg.astype(jnp.float32), kr.value.astype(jnp.float32),
            ) * (head_dim ** -0.5)
            scores = jnp.where(mask, scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            o = jnp.einsum(
                "bkgqs,bksd->bkgqd", probs,
                vr.value.astype(jnp.float32),
            ).reshape(b, self.heads, s, head_dim).astype(self.dtype)
        elif paged:
            # ---- paged KV decode -------------------------------------
            # The cache collection is a PROCESS-WIDE pool of fixed-size
            # pages; each row's logical KV sequence maps to physical
            # pages through ``page_table`` (B, n_pages) and rows write
            # at their own ``write_pos`` (B,) — physical position ==
            # logical position, no shared scalar index, no left-pads.
            # Writes whose ``write_mask`` is False are redirected to
            # page 0, the RESERVED write-sink: the allocator never maps
            # it into a live row's table, so masked rows (empty slots,
            # done rows, prefill tails past a row's width) scribble
            # garbage nobody ever reads instead of corrupting shared
            # pages. Reads gather the row's pages back into a dense
            # (B, KVH, L, D) view and ride the exact einsum+mask path
            # of the contiguous cache below (a fused TPU kernel would
            # replace the gather; on the XLA path the gather is the
            # page-table lookup).
            if self.kv_quant not in (None, "int8"):
                raise ValueError(
                    f"kv_quant must be None or 'int8', got {self.kv_quant!r}"
                )
            ps = int(self.kv_page_size)
            npages = int(self.kv_pages)
            store_dtype = jnp.int8 if self.kv_quant == "int8" else self.dtype
            # checked BEFORE self.variable() below creates the pools —
            # the init pass must take the shapes-only branch
            ready = self.has_variable("cache", "key_pages")
            kp = self.variable("cache", "key_pages", jnp.zeros,
                               (npages, kvh, ps, head_dim), store_dtype)
            vp = self.variable("cache", "value_pages", jnp.zeros,
                               (npages, kvh, ps, head_dim), store_dtype)
            if self.kv_quant == "int8":
                ksc = self.variable("cache", "key_scales", jnp.zeros,
                                    (npages, ps), jnp.float32)
                vsc = self.variable("cache", "value_scales", jnp.zeros,
                                    (npages, ps), jnp.float32)
            if ready:
                if page_table is None or write_pos is None:
                    raise ValueError(
                        "paged decode needs page_table and write_pos"
                    )
                n_row_pages = page_table.shape[1]
                max_len = n_row_pages * ps
                pos = write_pos[:, None] + jnp.arange(s, dtype=jnp.int32)
                # rotary positions ARE the logical positions (pad-free
                # by construction)
                q, k = rotary_embed(q, k, pos, self.rope_theta,
                                    self.rope_scaling,
                                    self.rope_scaling_kind)
                wm = (jnp.ones((b, s), bool) if write_mask is None
                      else write_mask)
                use_kernel = self.paged_kernel
                if use_kernel is None:
                    from tpuflow.core.hw import is_tpu_backend

                    use_kernel = is_tpu_backend()
                if use_kernel and s == 1 and self.kv_quant is None:
                    # fused path (ISSUE 11): token write + page-table-
                    # indirected blockwise online-softmax read in ONE
                    # kernel call — no dense (B, KVH, L, D) gather;
                    # the stores alias through input_output_aliases,
                    # so under the serve executables' buffer donation
                    # the page write is genuinely in place
                    from tpuflow.ops.attention import paged_flash_decode

                    o, kp.value, vp.value = paged_flash_decode(
                        q[:, :, 0, :], k[:, :, 0, :], v[:, :, 0, :],
                        kp.value, vp.value, page_table, pos[:, 0],
                        wm[:, 0], window=self.attn_window,
                    )
                    o = o[:, :, None, :].astype(self.dtype)
                else:
                    pg = jnp.take_along_axis(
                        page_table,
                        jnp.clip(pos // ps, 0, n_row_pages - 1),
                        axis=1,
                    )  # (B, s) physical page of each written position
                    pg = jnp.where(wm, pg, 0)  # masked writes → sink
                    off = pos % ps
                    kt = k.transpose(0, 2, 1, 3)  # (B, s, KVH, D)
                    vt = v.transpose(0, 2, 1, 3)
                    if self.kv_quant == "int8":
                        kq, ks_ = _kv_quant_int8(kt)
                        vq, vs_ = _kv_quant_int8(vt)
                        kp.value = kp.value.at[pg, :, off, :].set(kq)
                        vp.value = vp.value.at[pg, :, off, :].set(vq)
                        ksc.value = ksc.value.at[pg, off].set(ks_)
                        vsc.value = vsc.value.at[pg, off].set(vs_)
                        kf = (kp.value[page_table].astype(jnp.float32)
                              * ksc.value[page_table][:, :, None, :,
                                                      None])
                        vf = (vp.value[page_table].astype(jnp.float32)
                              * vsc.value[page_table][:, :, None, :,
                                                      None])
                    else:
                        kp.value = kp.value.at[pg, :, off, :].set(kt)
                        vp.value = vp.value.at[pg, :, off, :].set(vt)
                        kf = kp.value[page_table]
                        vf = vp.value[page_table]
                    # (B, n_pages, KVH, ps, D) → dense (B, KVH, L, D)
                    kf = kf.transpose(0, 2, 1, 3, 4).reshape(
                        b, kvh, max_len, head_dim)
                    vf = vf.transpose(0, 2, 1, 3, 4).reshape(
                        b, kvh, max_len, head_dim)
                    key_pos = jnp.arange(max_len)
                    # causal at logical granularity; stale page tails
                    # and table slots pointing at the sink page sit
                    # ABOVE each row's live index, so this one
                    # comparison masks them
                    ok = key_pos[None, None, :] <= pos[:, :, None]
                    if self.attn_window is not None:
                        ok = ok & (key_pos[None, None, :]
                                   > pos[:, :, None] - self.attn_window)
                    mask = ok[:, None, None]  # (B,1,1,s,L)
                    qg = q.reshape(b, kvh, group, s, head_dim)
                    scores = jnp.einsum(
                        "bkgqd,bksd->bkgqs",
                        qg.astype(jnp.float32), kf.astype(jnp.float32),
                    ) * (head_dim ** -0.5)
                    scores = jnp.where(mask, scores, -1e30)
                    probs = jax.nn.softmax(scores, axis=-1)
                    o = jnp.einsum(
                        "bkgqs,bksd->bkgqd", probs,
                        vf.astype(jnp.float32),
                    ).reshape(b, self.heads, s, head_dim).astype(
                        self.dtype)
            else:
                # init pass: shapes only (page pools created above)
                positions = jnp.arange(s, dtype=jnp.int32)
                q, k = rotary_embed(q, k, positions, self.rope_theta,
                                    self.rope_scaling,
                                    self.rope_scaling_kind)
                o = mha_xla(q, expand_kv(k), expand_kv(v), causal=True,
                            window=self.attn_window)
        elif self.decode:
            # KV cache (flax idiom): created at init time with the FULL
            # target length; decode calls then feed s<=full chunks which
            # are written at cache_index. The cache shapes fix max_len.
            ready = self.has_variable("cache", "cached_key")
            ck = self.variable("cache", "cached_key", jnp.zeros,
                               k.shape, k.dtype)
            cv = self.variable("cache", "cached_value", jnp.zeros,
                               v.shape, v.dtype)
            ci = self.variable("cache", "cache_index",
                               lambda: jnp.zeros((), jnp.int32))
            if ready:
                i = ci.value
                max_len = ck.value.shape[2]
                slots = i + jnp.arange(s, dtype=jnp.int32)  # cache slots
                if pad_lens is None:
                    positions = slots
                else:
                    # bucketed serving: rows are LEFT-padded to a shared
                    # bucket length, pad_lens[r] pad slots preceding row
                    # r's real tokens. Rotary positions are the LOGICAL
                    # (pad-free) offsets, so a padded row rotates exactly
                    # like its unpadded run; pad slots clamp to 0 (they
                    # are masked out of every attention read below).
                    positions = jnp.maximum(
                        slots[None, :] - pad_lens[:, None], 0
                    )
                q, k = rotary_embed(q, k, positions, self.rope_theta,
                                self.rope_scaling, self.rope_scaling_kind)
                ck.value = lax.dynamic_update_slice(ck.value, k, (0, 0, i, 0))
                cv.value = lax.dynamic_update_slice(cv.value, v, (0, 0, i, 0))
                ci.value = i + s
                # q rows attend to cache positions <= their own absolute
                # position (causal within the chunk, full to the past)
                key_pos = jnp.arange(max_len)
                ok = key_pos[None, :] <= slots[:, None]  # (s, max_len)
                if pad_lens is None:
                    if self.attn_window is not None:
                        # sliding window holds in decode too: each new
                        # token sees only its last attn_window entries
                        ok = ok & (key_pos[None, :] > slots[:, None]
                                   - self.attn_window)
                    mask = ok[None, None, None]  # (1,1,1,s,max_len)
                else:
                    # per-row mask: pad slots are never valid keys, and
                    # the sliding window counts LOGICAL distance so pads
                    # consume none of it
                    okb = ok[None] & (key_pos[None, None, :]
                                      >= pad_lens[:, None, None])
                    if self.attn_window is not None:
                        key_log = (key_pos[None, None, :]
                                   - pad_lens[:, None, None])
                        okb = okb & (key_log > positions[:, :, None]
                                     - self.attn_window)
                    mask = okb[:, None, None]  # (b,1,1,s,max_len)
                # grouped einsums against the SMALL (B, KVH, S, D)
                # cache — each K/V head serves its `group` query heads
                # without ever materializing an expanded cache (the
                # whole point of GQA at decode time); group == 1 is
                # plain MHA
                qg = q.reshape(b, kvh, group, s, head_dim)
                scores = jnp.einsum(
                    "bkgqd,bksd->bkgqs",
                    qg.astype(jnp.float32), ck.value.astype(jnp.float32),
                ) * (head_dim ** -0.5)
                scores = jnp.where(mask, scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1)
                o = jnp.einsum(
                    "bkgqs,bksd->bkgqd", probs,
                    cv.value.astype(jnp.float32),
                ).reshape(b, self.heads, s, head_dim).astype(self.dtype)
            else:
                # init pass: shapes only (cache created above)
                positions = jnp.arange(s, dtype=jnp.int32)
                q, k = rotary_embed(q, k, positions, self.rope_theta,
                                self.rope_scaling, self.rope_scaling_kind)
                o = mha_xla(q, expand_kv(k), expand_kv(v), causal=True,
                            window=self.attn_window)
        else:
            if self.seq_axis is not None:
                # absolute positions of this shard's tokens
                shard = lax.axis_index(self.seq_axis)
                if self.sp_layout == "striped":
                    nsh = _axis_size(self.seq_axis)
                    positions = shard + jnp.arange(s, dtype=jnp.int32) * nsh
                else:
                    positions = shard * s + jnp.arange(s, dtype=jnp.int32)
            else:
                positions = jnp.arange(s, dtype=jnp.int32)
            if positions_override is not None:
                positions = positions_override  # packed per-doc offsets
            q, k = rotary_embed(q, k, positions, self.rope_theta,
                                self.rope_scaling, self.rope_scaling_kind)

            if self.seq_axis is not None:
                # ring-prefill KV harvest (ISSUE 13): when the caller
                # passes mutable=['ring_kv'], expose this layer's
                # post-rotary K/V at KV-head granularity — the exact
                # tensors the paged decode cache stores — so a
                # sequence-parallel prompt pass can land its KV into
                # pages (infer.generate.ring_prefill_kv). sow into an
                # immutable collection is a no-op, so training paths
                # pay nothing.
                self.sow("ring_kv", "k", k)
                self.sow("ring_kv", "v", v)
                if self.attn_window is not None:
                    # closes the direct-TransformerLM bypass of the
                    # build_transformer_lm guard: a windowed ring would
                    # silently run FULL causal attention otherwise
                    raise ValueError(
                        "attn_window and seq_axis (ring attention) "
                        "cannot combine yet"
                    )
                o = ring_attention(q, expand_kv(k), expand_kv(v),
                                   axis_name=self.seq_axis,
                                   causal=True, layout=self.sp_layout)
            elif pick_attn_impl(s, self.attn_impl) == "flash":
                # the kernels handle GQA natively (K/V head index maps)
                # — the expanded K/V are never materialized
                o = flash_attention(q, k, v, causal=True,
                                    window=self.attn_window,
                                    segment_ids=segment_ids,
                                    bh_block=self.attn_bh_block)
            else:
                o = mha_xla(q, expand_kv(k), expand_kv(v), causal=True,
                            window=self.attn_window,
                            segment_ids=segment_ids)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, self.dim)
        return nn.Dense(
            self.dim,
            use_bias=False,
            dtype=self.dtype,
            kernel_init=_part(_dense_init, (MODEL_AXIS, None), tp),
            name="proj",
        )(o)


def _kv_quant_int8(t):
    """Per-token symmetric int8 quantization for paged KV storage:
    ``t`` (B, S, KVH, D) → ``(q int8, scale f32 (B, S))`` with one
    scale per TOKEN (= per page slot once scattered: the page's scale
    vector), amax over that token's (KVH, D) values. Dequant is
    ``q * scale`` in the attention read."""
    t32 = t.astype(jnp.float32)
    amax = jnp.max(jnp.abs(t32), axis=(2, 3))
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(t32 / scale[:, :, None, None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


class SwiGLU(nn.Module):
    dim: int
    hidden: int
    dtype: Any
    tp: bool = True

    @nn.compact
    def __call__(self, x):
        def col(name):
            return nn.Dense(
                self.hidden,
                use_bias=False,
                dtype=self.dtype,
                kernel_init=_part(_dense_init, (None, MODEL_AXIS), self.tp),
                name=name,
            )(x)

        y = nn.silu(col("gate")) * col("up")
        return nn.Dense(
            self.dim,
            use_bias=False,
            dtype=self.dtype,
            kernel_init=_part(_dense_init, (MODEL_AXIS, None), self.tp),
            name="down",
        )(y)


class DecoderBlock(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int
    dtype: Any
    attn_impl: str
    seq_axis: Optional[str]
    rope_theta: float = 10000.0
    n_experts: int = 0  # >0 → MoE MLP in this block
    moe_top_k: int = 2
    moe_no_drop: bool = False  # dropless routing (see MoEMlp.no_drop)
    ep_axis: Optional[str] = None
    decode: bool = False
    sp_layout: str = "contiguous"
    remat_mlp: bool = False  # checkpoint the MLP sub-block only
    attn_window: Optional[int] = None
    kv_heads: Optional[int] = None  # grouped-query attention (GQA)
    attn_bh_block: int = 1  # batched-bh flash grid (see CausalAttention)
    rope_scaling: float = 1.0  # RoPE context extension (see CausalAttention)
    rope_scaling_kind: str = "linear"  # linear | ntk
    kv_pages: Optional[int] = None  # paged KV cache (see CausalAttention)
    kv_page_size: int = 16
    kv_quant: Optional[str] = None
    paged_kernel: Optional[bool] = None  # fused decode (CausalAttention)

    @nn.compact
    def __call__(self, x, segment_ids=None, positions=None, pad_lens=None,
                 page_table=None, write_pos=None, write_mask=None):
        x = x + CausalAttention(
            self.dim, self.heads, self.dtype, self.attn_impl, self.seq_axis,
            self.rope_theta, self.decode, self.sp_layout,
            attn_window=self.attn_window, kv_heads=self.kv_heads,
            attn_bh_block=self.attn_bh_block,
            rope_scaling=self.rope_scaling,
            rope_scaling_kind=self.rope_scaling_kind,
            kv_pages=self.kv_pages, kv_page_size=self.kv_page_size,
            kv_quant=self.kv_quant, paged_kernel=self.paged_kernel,
            name="attn",
        )(RMSNorm(self.dtype, name="norm1")(x), segment_ids, positions,
          pad_lens, page_table, write_pos, write_mask)
        y = RMSNorm(self.dtype, name="norm2")(x)
        if self.n_experts > 0:
            from tpuflow.models.moe import MoEMlp

            y, aux = MoEMlp(
                self.dim, self.dim * self.mlp_ratio,
                n_experts=self.n_experts, top_k=self.moe_top_k,
                dtype=self.dtype, ep_axis=self.ep_axis,
                no_drop=self.moe_no_drop, name="moe",
            )(y)
            # accumulated under mutable=['losses']; no-op otherwise
            self.sow("losses", "moe_aux", aux)
        else:
            # remat_mlp: checkpoint ONLY the MLP sub-block — attention
            # (and the flash kernel's residuals) live OUTSIDE any remat
            # boundary, so the backward never replays the kernel; the
            # cheap SwiGLU GEMMs are what get recomputed. MoE blocks
            # skip this (their sow'd aux loss is a mutable side effect
            # lifted remat must not replay).
            mlp_cls = nn.remat(SwiGLU) if self.remat_mlp else SwiGLU
            y = mlp_cls(
                self.dim, self.dim * self.mlp_ratio, self.dtype,
                tp=self.seq_axis is None, name="mlp",
            )(y)
        return x + y


def lm_head_dot(x, kernel):
    """The LM head matmul: both operands in the ACTIVATION dtype with
    float32 accumulation — bf16 models stay on the full-rate MXU path
    (an f32-operand matmul over a 32k vocab runs ~4-8x slower and was
    measured dominating the LM step's tail) while the logits come out
    float32 for the loss. ONE definition shared by :class:`LMHead` and
    the pipeline trainer's in-stage head, so the two can never drift
    numerically (their loss-parity tests depend on it)."""
    return jax.lax.dot_general(
        x, kernel.astype(x.dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def lm_head_dot_tied(x, embed):
    """Tied-embeddings head: logits = x · embedᵀ with the embedding
    table used AS the head kernel — contraction over the last dim of
    both operands, so the transpose never materializes. Same dtype
    discipline as :func:`lm_head_dot`."""
    return jax.lax.dot_general(
        x, embed.astype(x.dtype),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


class LMHead(nn.Module):
    """Vocab projection (column-parallel under TP) via
    :func:`lm_head_dot`; the kernel param itself remains a float32
    master weight. ``project=False`` CREATES the param but returns the
    hidden states untouched — the skip_head mode of TransformerLM,
    which keeps the parameter tree identical so checkpoints/packaging
    see one layout while a fused loss (tpuflow.ops.xent) consumes the
    kernel directly."""

    vocab_size: int
    tp: bool
    project: bool = True

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel",
            _part(_dense_init, (None, MODEL_AXIS), self.tp),
            (x.shape[-1], self.vocab_size),
            jnp.float32,
        )
        return lm_head_dot(x, kernel) if self.project else x


class TransformerLM(nn.Module):
    """Causal LM: token ids (B, S) int32 → logits (B, S, vocab) f32."""

    vocab_size: int = 32000
    dim: int = 512
    depth: int = 6
    heads: int = 8
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    attn_impl: str = "auto"
    seq_axis: Optional[str] = None
    rope_theta: float = 10000.0
    n_experts: int = 0  # >0 → MoE MLP in every moe_every-th block
    moe_every: int = 2
    moe_top_k: int = 2
    moe_no_drop: bool = False  # dropless routing (serving; MoEMlp.no_drop)
    ep_axis: Optional[str] = None
    decode: bool = False  # autoregressive KV-cache mode (see infer.generate)
    remat: bool = False  # gradient checkpointing per block (long context)
    remat_policy: str = "full"  # 'full' | 'attn' (save attention outputs)
    sp_layout: str = "contiguous"  # see CausalAttention.sp_layout
    skip_head: bool = False  # return final-norm hidden states, not logits
    attn_window: Optional[int] = None  # sliding-window (local) attention
    kv_heads: Optional[int] = None  # grouped-query attention (GQA/MQA)
    attn_bh_block: int = 1  # batched-bh flash grid (see CausalAttention)
    rope_scaling: float = 1.0  # RoPE context extension (see CausalAttention)
    rope_scaling_kind: str = "linear"  # linear | ntk
    # weight tying: reuse the embedding table as the LM head (GPT-2 /
    # Gemma style) — drops the (dim, vocab) head parameter entirely
    tie_embeddings: bool = False
    # paged KV cache for decode mode (see CausalAttention.kv_pages):
    # page pools + per-call page_table/write_pos indirection
    kv_pages: Optional[int] = None
    kv_page_size: int = 16
    kv_quant: Optional[str] = None
    paged_kernel: Optional[bool] = None  # fused decode (CausalAttention)
    # ViT-prefix VLM (ISSUE 18): ids in [vocab_size, vocab_size +
    # image_vocab) embed through a SEPARATE per-patch-token table (the
    # learned patch embedding — models.vlm maps image patches to these
    # ids deterministically). The LM head stays text-vocab-wide, so
    # image tokens can appear only in prompts, never in samples.
    image_vocab: int = 0

    @nn.compact
    def __call__(self, tokens, train: bool = False, segment_ids=None,
                 positions=None, pad_lens=None, page_table=None,
                 write_pos=None, write_mask=None):
        tp = self.seq_axis is None
        if segment_ids is not None and (
                self.seq_axis is not None or self.decode):
            raise ValueError(
                "segment_ids (sequence packing) is not supported with "
                "seq_axis (ring attention) or decode mode"
            )
        if pad_lens is not None and not self.decode:
            raise ValueError(
                "pad_lens (bucketed left-padding) requires decode mode"
            )
        embed = self.param(
            "embed",
            _part(nn.initializers.normal(0.02), (MODEL_AXIS, None), tp),
            (self.vocab_size, self.dim),
            jnp.float32,
        )
        if self.image_vocab > 0:
            # two-table embed: image-prefix ids (>= vocab_size) gather
            # from the patch-token table, everything else from the text
            # table. Both gathers run (clipped ids), the where selects
            # — static shapes, no data-dependent control flow. Past
            # the embed, image tokens are ordinary positions: packing,
            # pad_lens, pages, prefix chunk keys all apply unchanged.
            img_embed = self.param(
                "img_embed",
                _part(nn.initializers.normal(0.02),
                      (MODEL_AXIS, None), tp),
                (self.image_vocab, self.dim),
                jnp.float32,
            )
            is_img = tokens >= self.vocab_size
            txt = jnp.take(
                embed, jnp.clip(tokens, 0, self.vocab_size - 1), axis=0)
            img = jnp.take(
                img_embed,
                jnp.clip(tokens - self.vocab_size, 0,
                         self.image_vocab - 1),
                axis=0)
            x = jnp.where(is_img[..., None], img, txt).astype(self.dtype)
        else:
            x = jnp.take(embed, tokens, axis=0).astype(self.dtype)
        # remat trades FLOPs for HBM: 'full' checkpoints whole blocks
        # (activations recomputed in the backward — the standard
        # long-context lever, pairing with the ring's O(seq/sp)
        # residency). remat_policy='attn' instead checkpoints ONLY each
        # block's MLP sub-module: the attention residuals (including
        # the flash kernel's output/lse) stay resident by construction,
        # so the backward never replays the kernel and only the cheap
        # SwiGLU GEMMs recompute — the middle rung between full remat
        # and no remat. Not in decode mode: the KV cache is a mutable
        # collection, which lifted remat must not replay.
        if self.remat_policy not in ("full", "attn"):
            raise ValueError(
                f"remat_policy must be 'full' or 'attn', got "
                f"{self.remat_policy!r}"
            )
        use_remat = self.remat and not self.decode
        remat_mlp = use_remat and self.remat_policy == "attn"
        block_cls = (
            nn.remat(DecoderBlock)
            if use_remat and self.remat_policy == "full"
            else DecoderBlock
        )
        for i in range(self.depth):
            moe_block = self.n_experts > 0 and (i % self.moe_every
                                                == self.moe_every - 1)
            x = block_cls(
                self.dim, self.heads, self.mlp_ratio, self.dtype,
                self.attn_impl, self.seq_axis, self.rope_theta,
                n_experts=self.n_experts if moe_block else 0,
                moe_top_k=self.moe_top_k,
                moe_no_drop=self.moe_no_drop, ep_axis=self.ep_axis,
                decode=self.decode, sp_layout=self.sp_layout,
                remat_mlp=remat_mlp and not moe_block,
                attn_window=self.attn_window,
                kv_heads=self.kv_heads,
                attn_bh_block=self.attn_bh_block,
                rope_scaling=self.rope_scaling,
                rope_scaling_kind=self.rope_scaling_kind,
                kv_pages=self.kv_pages, kv_page_size=self.kv_page_size,
                kv_quant=self.kv_quant, paged_kernel=self.paged_kernel,
                name=f"block{i}",
            )(x, segment_ids, positions, pad_lens, page_table,
              write_pos, write_mask)
        x = RMSNorm(self.dtype, name="norm_final")(x)
        if self.tie_embeddings:
            # tied head: the embedding table IS the head kernel (its
            # vocab-axis sharding makes the logits column-parallel,
            # same as the untied head); no lm_head param exists
            return x if self.skip_head else lm_head_dot_tied(x, embed)
        # vocab-sharded LM head (column-parallel); logits in float32.
        # skip_head keeps the param (identical tree) but returns the
        # hidden states for a fused linear+loss (tpuflow.ops.xent)
        return LMHead(
            self.vocab_size, tp, project=not self.skip_head,
            name="lm_head",
        )(x)


def build_transformer_lm(
    vocab_size: int = 32000,
    dim: int = 512,
    depth: int = 6,
    heads: int = 8,
    mlp_ratio: int = 4,
    dtype: Any = jnp.bfloat16,
    attn_impl: str = "auto",
    seq_axis: Optional[str] = None,
    n_experts: int = 0,
    moe_every: int = 2,
    moe_top_k: int = 2,
    moe_no_drop: bool = False,
    ep_axis: Optional[str] = None,
    remat: bool = False,
    remat_policy: str = "full",
    sp_layout: str = "contiguous",
    attn_window: Optional[int] = None,
    kv_heads: Optional[int] = None,
    tie_embeddings: bool = False,
    attn_bh_block: int = 1,
    rope_scaling: float = 1.0,
    rope_scaling_kind: str = "linear",
    image_vocab: int = 0,
) -> TransformerLM:
    if dim % heads:
        raise ValueError("dim must be a multiple of heads")
    if image_vocab < 0:
        raise ValueError(
            f"image_vocab must be >= 0 (size of the patch-token table; "
            f"0 = text-only), got {image_vocab}"
        )
    if n_experts > 0 and moe_top_k > n_experts:
        raise ValueError(
            f"moe_top_k ({moe_top_k}) cannot exceed n_experts "
            f"({n_experts}) — each token routes to top_k DISTINCT "
            "experts"
        )
    if kv_heads is not None:
        if kv_heads < 1 or heads % kv_heads:
            raise ValueError(
                f"kv_heads ({kv_heads}) must divide heads ({heads}) — "
                "each K/V head serves heads//kv_heads query heads (GQA)"
            )
    if (dim // heads) % 2:
        raise ValueError("head_dim must be even (rotary pairs)")
    if rope_scaling < 1.0:
        raise ValueError(
            f"rope_scaling must be >= 1.0 (a context-EXTENSION factor), "
            f"got {rope_scaling}"
        )
    if rope_scaling_kind not in ("linear", "ntk"):
        raise ValueError(
            f"rope_scaling_kind must be 'linear' or 'ntk', got "
            f"{rope_scaling_kind!r}"
        )
    if sp_layout not in ("contiguous", "striped"):
        raise ValueError(
            f"sp_layout must be contiguous|striped, got {sp_layout!r}"
        )
    if sp_layout == "striped" and seq_axis is None:
        raise ValueError("sp_layout='striped' requires seq_axis")
    if attn_window is not None:
        if seq_axis is not None:
            raise ValueError(
                "attn_window and seq_axis (ring attention) cannot "
                "combine yet — a windowed ring would skip whole ring "
                "hops; use one or the other"
            )
        if attn_window < 1:
            raise ValueError(f"attn_window must be >= 1, got {attn_window}")
    return TransformerLM(
        vocab_size=vocab_size, dim=dim, depth=depth, heads=heads,
        mlp_ratio=mlp_ratio, dtype=dtype, attn_impl=attn_impl,
        seq_axis=seq_axis, n_experts=n_experts, moe_every=moe_every,
        moe_top_k=moe_top_k, moe_no_drop=moe_no_drop, ep_axis=ep_axis,
        remat=remat,
        remat_policy=remat_policy, sp_layout=sp_layout,
        attn_window=attn_window, kv_heads=kv_heads,
        tie_embeddings=tie_embeddings, attn_bh_block=attn_bh_block,
        rope_scaling=rope_scaling, rope_scaling_kind=rope_scaling_kind,
        image_vocab=image_vocab,
    )


def draft_lm_config(base_config: Dict[str, Any], *,
                    dim: Optional[int] = None, depth: int = 1,
                    heads: Optional[int] = None,
                    mlp_ratio: Optional[int] = None,
                    kv_heads: Optional[int] = None) -> Dict[str, Any]:
    """Derive a DRAFT-model build config from a target's
    :func:`build_transformer_lm` kwargs (speculative decoding,
    ISSUE 9): the vocabulary, dtype, RoPE scaling and embedding-tying
    are inherited (they must agree for the draft's token stream and
    positions to mean the same thing), while the size knobs shrink —
    default ``dim`` is a quarter of the target's (floored at 32) and
    ``depth`` is 1. ``heads`` defaults to the largest power-of-two
    divisor of the target's head count that keeps ``head_dim`` even.

    Draft quality only moves the ACCEPTANCE RATE — the oracle-parity
    acceptance rule makes outputs token-identical to the target's own
    decode no matter what the draft proposes — so a draft config is a
    throughput tuning knob, not a correctness surface.

    An MoE target (``n_experts > 0`` in the base config) derives a
    DENSE draft deliberately: the expert stack is never copied (a
    quarter-dim draft carrying E expert MLPs would erase the
    cheap-draft break-even, ISSUE 9's caveat), and acceptance-parity
    means the dense draft can only cost acceptance rate, never
    correctness. A VLM target's ``image_vocab`` IS inherited — the
    draft must embed the same image-prefix ids or drafted rows would
    read garbage prompt positions."""
    base = dict(base_config)
    if dim is None:
        # even: rotary halves head_dim, and heads=1 must stay legal
        dim = max(32, (int(base.get("dim", 512)) // 4) & ~1)
    dim = int(dim)
    if dim % 2:
        raise ValueError(
            f"draft dim must be even (rotary splits head_dim in two; "
            f"heads=1 would leave head_dim={dim}), got {dim}")
    if heads is None:
        h = int(base.get("heads", 8))
        while h > 1 and (dim % h or (dim // h) % 2):
            h //= 2
        heads = max(1, h)
    cfg: Dict[str, Any] = {
        "vocab_size": base.get("vocab_size", 32000),
        "dim": dim,
        "depth": int(depth),
        "heads": int(heads),
        "mlp_ratio": int(mlp_ratio if mlp_ratio is not None
                         else base.get("mlp_ratio", 4)),
        "dtype": base.get("dtype", jnp.bfloat16),
        "attn_impl": base.get("attn_impl", "auto"),
        "rope_scaling": base.get("rope_scaling", 1.0),
        "rope_scaling_kind": base.get("rope_scaling_kind", "linear"),
        "tie_embeddings": base.get("tie_embeddings", False),
    }
    if int(base.get("image_vocab", 0) or 0) > 0:
        cfg["image_vocab"] = int(base["image_vocab"])
    if kv_heads is not None:
        cfg["kv_heads"] = int(kv_heads)
    return cfg


def share_draft_embeddings(draft_params, target_params):
    """The shared-embedding option for draft models: graft the
    TARGET's token-embedding table (and, when the shapes agree, its LM
    head kernel) into a draft's param tree — the standard trick that
    hands a fresh draft the target's token geometry for free. Returns
    a NEW param dict sharing the target's arrays (no copies: the
    device buffers are literally shared, so the ledger bytes don't
    double). Requires ``draft dim == target dim`` — raises
    ``ValueError`` otherwise (the embedding is (vocab, dim))."""
    te = target_params["embed"]
    de = draft_params["embed"]
    if tuple(te.shape) != tuple(de.shape):
        raise ValueError(
            f"shared embeddings need matching (vocab, dim) tables: "
            f"target {tuple(te.shape)} vs draft {tuple(de.shape)} — "
            f"build the draft with the target's dim (draft_lm_config("
            f"..., dim=target_dim)) or skip sharing"
        )
    out = dict(draft_params)
    out["embed"] = te
    # VLM drafts: share the patch-token table too when both trees
    # carry one of the same shape (same image_vocab and dim)
    ti = target_params.get("img_embed")
    di = draft_params.get("img_embed")
    if (ti is not None and di is not None
            and tuple(ti.shape) == tuple(di.shape)):
        out["img_embed"] = ti
    th = target_params.get("lm_head")
    dh = draft_params.get("lm_head")
    if (isinstance(th, dict) and isinstance(dh, dict)
            and "kernel" in th and "kernel" in dh
            and tuple(th["kernel"].shape) == tuple(dh["kernel"].shape)):
        out["lm_head"] = dict(dh, kernel=th["kernel"])
    return out


def perplexity(loss: float) -> float:
    """exp(loss) with the standard overflow clamp — THE ppl definition
    shared by LMTrainer metrics and PackagedLM.score (one clamp, one
    place)."""
    import numpy as np

    return float(np.exp(min(float(loss), 20.0)))


def token_loss(logits, targets, mask=None, ignore_index: int = -1,
               label_smoothing: float = 0.0):
    """Mean cross-entropy of ``logits[:, i]`` predicting
    ``targets[:, i]`` — the UNSHIFTED general form (the caller aligns
    predictions with targets; :func:`next_token_loss` is this plus the
    standard one-position shift). ``mask`` (optional, broadcastable to
    targets' shape) excludes positions; positions whose target equals
    ``ignore_index`` are always excluded. Used directly by the striped
    sequence-parallel trainer, which keeps logits in the ring's striped
    order and permutes only the (vocab-times smaller) integer targets.

    ``label_smoothing``: uniform smoothing without materializing a
    (B, S, vocab) one-hot — smoothed NLL decomposes as
    ``(1-ε)·nll(target) + ε·mean_v nll(v)``.
    """
    import optax

    if not 0.0 <= label_smoothing < 1.0:
        raise ValueError(
            f"label_smoothing must be in [0, 1), got {label_smoothing}"
        )
    pred = logits.astype(jnp.float32)
    # targets outside [0, vocab) fold into the ignore mask — the same
    # convention as ops.xent.fused_linear_token_loss, so corrupt data
    # gives the SAME (zero) contribution on both loss paths instead of
    # two different wrong answers (ADVICE r03)
    in_range = (targets >= 0) & (targets < pred.shape[-1])
    valid = ((targets != ignore_index) & in_range).astype(jnp.float32)
    if mask is not None:
        valid = valid * mask.astype(jnp.float32)
    safe_targets = jnp.where(in_range & (targets != ignore_index), targets, 0)
    if label_smoothing:
        logp = jax.nn.log_softmax(pred, axis=-1)
        nll_t = -jnp.take_along_axis(
            logp, safe_targets[..., None], axis=-1
        )[..., 0]
        nll_u = -jnp.mean(logp, axis=-1)
        losses = (1.0 - label_smoothing) * nll_t + label_smoothing * nll_u
    else:
        losses = optax.softmax_cross_entropy_with_integer_labels(
            pred, safe_targets
        )
    return jnp.sum(losses * valid) / jnp.maximum(jnp.sum(valid), 1.0)


def packed_segments(tokens, eos_id: int):
    """Derive sequence-packing metadata from EOS-delimited rows —
    fully on-device (vectorized cumsum/cummax), so packed corpora need
    NO extra arrays over the link: the token stream itself carries the
    document structure.

    Returns ``(segment_ids, positions, target_mask)``:

    - ``segment_ids`` (B, S) int32: document index per position; the
      EOS token belongs to the document it terminates.
    - ``positions`` (B, S) int32: 0-based offset within the document
      (rotary restarts per document).
    - ``target_mask`` (B, S-1) float32, aligned with ``tokens[:, 1:]``
      as next-token targets: 1 where target t+1 belongs to the SAME
      document as position t — the prediction "first token of the next
      document from my EOS" carries no signal and is masked.
    """
    is_eos = (tokens == eos_id).astype(jnp.int32)
    seg = jnp.cumsum(is_eos, axis=1) - is_eos  # EOS stays in its doc
    ar = jnp.broadcast_to(
        jnp.arange(tokens.shape[1], dtype=jnp.int32), tokens.shape
    )
    is_start = jnp.concatenate(
        [jnp.ones_like(seg[:, :1], bool), seg[:, 1:] != seg[:, :-1]],
        axis=1,
    )
    start = jax.lax.cummax(jnp.where(is_start, ar, 0), axis=1)
    positions = ar - start
    target_mask = (seg[:, 1:] == seg[:, :-1]).astype(jnp.float32)
    return seg, positions, target_mask


def next_token_loss(logits, tokens, ignore_index: int = -1,
                    label_smoothing: float = 0.0):
    """Mean cross-entropy of logits[:, :-1] predicting tokens[:, 1:].

    Positions whose TARGET equals ``ignore_index`` are masked out.
    Use on global (unsharded or batch-sharded) arrays; under sequence
    parallelism apply to the all-gathered logits or compute the shifted
    targets outside the shard_map so the shift crosses shard boundaries
    correctly.
    """
    return token_loss(
        logits[:, :-1], tokens[:, 1:], ignore_index=ignore_index,
        label_smoothing=label_smoothing,
    )
