"""Vision Transformer classifier — the attention model family.

The reference's only model is a frozen MobileNetV2 + head
(P1/02_model_training_single_node.py:159-178); this adds the attention
family the TPU build treats as first-class (long context, tensor/
sequence parallelism — SURVEY.md §2c "TPU-native plan" column):

- **Tensor parallelism**: every transformer weight carries a
  ``nn.with_partitioning`` annotation over the mesh ``model`` axis
  (attention heads and MLP hidden column-sharded, output projections
  row-sharded). Under ``jit`` with a (data, model) mesh, GSPMD shards
  the matmuls and inserts the reduce-scatters/all-reduces — the
  idiomatic XLA path (no hand-written collectives).
- **Sequence parallelism**: ``seq_axis="seq"`` switches the module into
  manual mode for use inside ``shard_map`` with images sharded along H:
  attention becomes ring attention (K/V rotating over ICI,
  tpuflow.parallel.ring_attention), the positional table is sliced per
  shard, and token pooling becomes a psum-mean. Everything else is
  per-token and needs no communication.
- **Attention impls**: ``attn_impl='auto'`` resolves per sequence
  length (tpuflow.ops.pick_attn_impl): XLA einsums for short vision
  sequences (fully GSPMD-partitionable, one fused chain), the Pallas
  blockwise kernel on TPU once the O(S^2) score matrix is worth
  avoiding; ``'flash'``/``'einsum'`` force either path.

Mean-pool classification (no CLS token) keeps every op shard-uniform.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from tpuflow.core.compat import axis_size as _axis_size
from tpuflow.ops.attention import flash_attention, mha_xla, pick_attn_impl
from tpuflow.parallel.mesh import MODEL_AXIS
from tpuflow.parallel.ring_attention import ring_attention


from tpuflow.models._layers import dense_init as _dense_init  # noqa: E402
from tpuflow.models._layers import part as _part  # noqa: E402


class ViTMlp(nn.Module):
    dim: int
    hidden: int
    dropout: float
    dtype: Any
    tp: bool = True

    @nn.compact
    def __call__(self, x, deterministic: bool):
        x = nn.Dense(
            self.hidden,
            dtype=self.dtype,
            kernel_init=_part(_dense_init, (None, MODEL_AXIS), self.tp),
            bias_init=_part(nn.initializers.zeros_init(), (MODEL_AXIS,), self.tp),
            name="fc_in",
        )(x)
        x = nn.gelu(x)
        x = nn.Dropout(self.dropout)(x, deterministic=deterministic)
        x = nn.Dense(
            self.dim,
            dtype=self.dtype,
            kernel_init=_part(_dense_init, (MODEL_AXIS, None), self.tp),
            name="fc_out",
        )(x)
        return x


class ViTAttention(nn.Module):
    dim: int
    heads: int
    dtype: Any
    attn_impl: str = "auto"  # auto | flash
    seq_axis: Optional[str] = None  # set → ring attention inside shard_map

    @nn.compact
    def __call__(self, x, deterministic: bool):
        # Megatron-style rank-2 projections: q/k/v column-sharded, the
        # output projection row-sharded (one all-reduce per block under
        # GSPMD). Column chunks are contiguous heads ((H, D) row-major),
        # so the model axis shards whole heads when it divides `heads`.
        tp = self.seq_axis is None
        head_dim = self.dim // self.heads

        def proj_in(name):
            return nn.Dense(
                self.dim,
                dtype=self.dtype,
                kernel_init=_part(_dense_init, (None, MODEL_AXIS), tp),
                bias_init=_part(nn.initializers.zeros_init(), (MODEL_AXIS,), tp),
                name=name,
            )(x)

        b, s, _ = x.shape

        def heads_first(t):  # (B, S, C) → (B, H, S, D)
            return t.reshape(b, s, self.heads, head_dim).transpose(0, 2, 1, 3)

        q, k, v = (heads_first(proj_in(n)) for n in ("query", "key", "value"))
        if self.seq_axis is not None:
            o = ring_attention(q, k, v, axis_name=self.seq_axis)
        elif pick_attn_impl(s, self.attn_impl) == "flash":
            o = flash_attention(q, k, v)
        else:
            o = mha_xla(q, k, v)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, self.dim)
        return nn.Dense(
            self.dim,
            dtype=self.dtype,
            kernel_init=_part(_dense_init, (MODEL_AXIS, None), tp),
            name="proj",
        )(o)


class ViTBlock(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int
    dropout: float
    dtype: Any
    attn_impl: str
    seq_axis: Optional[str]

    @nn.compact
    def __call__(self, x, deterministic: bool):
        y = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        y = ViTAttention(
            self.dim,
            self.heads,
            self.dtype,
            self.attn_impl,
            self.seq_axis,
            name="attn",
        )(y, deterministic)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        y = ViTMlp(
            self.dim,
            self.dim * self.mlp_ratio,
            self.dropout,
            self.dtype,
            tp=self.seq_axis is None,
            name="mlp",
        )(y, deterministic)
        return x + y


class ViTClassifier(nn.Module):
    """ViT image classifier; plugs into Trainer exactly like
    TransferClassifier (logits out, 'dropout' rng, no batch_stats)."""

    num_classes: int = 5
    patch_size: int = 16
    width: int = 192
    depth: int = 6
    heads: int = 6
    mlp_ratio: int = 4
    dropout: float = 0.1
    num_patches: int = 196  # global token count (for the positional table)
    dtype: Any = jnp.bfloat16
    attn_impl: str = "auto"
    seq_axis: Optional[str] = None
    freeze_backbone: bool = False  # API parity with TransferClassifier
    remat: bool = False  # gradient checkpointing per block

    @nn.compact
    def __call__(self, x, train: bool = False):
        p = self.patch_size
        # Non-overlapping patch embed: under sequence parallelism each
        # shard holds a contiguous slab of image rows, so patch order is
        # globally row-major and shards stay contiguous.
        x = nn.Conv(
            self.width,
            (p, p),
            strides=(p, p),
            dtype=self.dtype,
            name="patch_embed",
        )(x)
        b, hh, ww, c = x.shape
        x = x.reshape(b, hh * ww, c)

        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, self.num_patches, self.width),
            jnp.float32,
        )
        if self.seq_axis is not None:
            # slice this shard's rows of the global positional table
            n_shards = _axis_size(self.seq_axis)
            if hh * ww * n_shards != self.num_patches:
                raise ValueError(
                    f"got {hh * ww} local patches x {n_shards} shards, model "
                    f"configured for {self.num_patches} global patches "
                    "(image size / patch_size / shard-count mismatch)"
                )
            shard = lax.axis_index(self.seq_axis)
            pos = lax.dynamic_slice_in_dim(pos, shard * (hh * ww), hh * ww, axis=1)
        else:
            if hh * ww != self.num_patches:
                raise ValueError(
                    f"got {hh * ww} patches, model configured for "
                    f"{self.num_patches} (image size / patch_size mismatch)"
                )
        x = x + pos.astype(self.dtype)
        x = nn.Dropout(self.dropout)(x, deterministic=not train)

        # remat: recompute block activations in the backward instead of
        # storing them — HBM for FLOPs, the long-context/memory lever.
        # ``deterministic`` must stay a PYTHON bool through the
        # checkpoint boundary (flax Dropout branches on it): pass it
        # POSITIONALLY (static_argnums cannot mark kwargs) and mark
        # argnum 2 static — linen numbering counts the module itself,
        # so (module, x, deterministic) → 2.
        block_cls = (
            nn.remat(ViTBlock, static_argnums=(2,)) if self.remat
            else ViTBlock
        )
        for i in range(self.depth):
            x = block_cls(
                self.width,
                self.heads,
                self.mlp_ratio,
                self.dropout,
                self.dtype,
                self.attn_impl,
                self.seq_axis,
                name=f"block{i}",
            )(x, not train)

        x = nn.LayerNorm(dtype=self.dtype, name="ln_final")(x)
        if self.seq_axis is not None:
            # global mean over the sharded token axis: psum of local sums
            # (uniform shards ⇒ the divisor is static)
            local = jnp.sum(x, axis=1)
            total = lax.psum(local, self.seq_axis)
            x = total / (hh * ww * _axis_size(self.seq_axis))
        else:
            x = jnp.mean(x, axis=1)
        x = nn.Dropout(self.dropout)(x, deterministic=not train)
        return nn.Dense(
            self.num_classes, dtype=jnp.float32, name="head_dense"
        )(x.astype(jnp.float32))


def build_vit(
    num_classes: int = 5,
    img_size: int = 224,
    patch_size: int = 16,
    width: int = 192,
    depth: int = 6,
    heads: int = 6,
    dropout: float = 0.1,
    dtype: Any = jnp.bfloat16,
    attn_impl: str = "auto",
    seq_axis: Optional[str] = None,
    remat: bool = False,
) -> ViTClassifier:
    if img_size % patch_size:
        raise ValueError("img_size must be a multiple of patch_size")
    n = (img_size // patch_size) ** 2
    return ViTClassifier(
        num_classes=num_classes,
        patch_size=patch_size,
        width=width,
        depth=depth,
        heads=heads,
        dropout=dropout,
        num_patches=n,
        dtype=dtype,
        attn_impl=attn_impl,
        seq_axis=seq_axis,
        remat=remat,
    )
