"""Pretrained MobileNetV2 backbone weights (C6).

The reference's transfer model starts from an ImageNet-PRETRAINED
backbone — ``tf.keras.applications.MobileNetV2(include_top=False,
...)`` ships ``weights='imagenet'`` by default (reference
P1/02_model_training_single_node.py:164-169) — and freezes it. Freezing
a randomly initialized backbone is semantically empty, so this module
makes the pretrained story real without any network access:

- **Canonical checkpoint format**: a ``.npz`` whose keys are
  '/'-joined, BACKBONE-RELATIVE Flax paths —
  ``params/stem/conv/kernel``, ``batch_stats/block_1_0/expand/bn/mean``
  — so the file is independent of the wrapper model that embeds the
  backbone.
- **Offline converters** from the two common public sources:
  torchvision's ``mobilenet_v2`` state_dict (``.pth``, loaded with
  ``torch.load``) and Keras's ``mobilenet_v2`` weight file (``.h5``,
  read with h5py). Run where those files exist:
  ``python -m tpuflow.models.pretrained torch_or_h5_file out.npz``.
- **Loader** that merges the file into an initialized model's
  variables with full shape verification (every file tensor must land
  somewhere; every backbone tensor must be covered — loud failure
  beats silently-random weights).

Wired through ``build_model(weights=...)`` → ``Trainer.init_state``
(the head stays freshly initialized; only the backbone is replaced).
"""

from __future__ import annotations

import sys
from typing import Any, Dict, Optional, Tuple

import numpy as np

SEP = "/"

# (expand t, channels c, repeats n) — mirrors mobilenet_v2.py settings;
# used to enumerate block names in source-checkpoint order
_SETTINGS = ((1, 16, 1), (6, 24, 2), (6, 32, 3), (6, 64, 4), (6, 96, 3),
             (6, 160, 3), (6, 320, 1))


def _block_names():
    for si, (_t, _c, n) in enumerate(_SETTINGS):
        for i in range(n):
            yield f"block_{si}_{i}", _t, si, i


# ---------------------------------------------------------------------------
# canonical npz format
# ---------------------------------------------------------------------------


def flatten_tree(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(flatten_tree(v, f"{prefix}{k}{SEP}"))
    else:
        out[prefix[: -len(SEP)]] = np.asarray(tree)
    return out


def unflatten_tree(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    for key, val in flat.items():
        parts = key.split(SEP)
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = val
    return root


def save_backbone_npz(path: str, params: Dict, batch_stats: Dict) -> None:
    """Save a backbone's params + BN statistics in the canonical format."""
    flat = flatten_tree({"params": params, "batch_stats": batch_stats})
    np.savez(path, **flat)


def load_backbone_npz(path: str) -> Tuple[Dict, Dict]:
    """Load canonical npz → (params_tree, batch_stats_tree)."""
    with np.load(path) as z:
        flat = {k: z[k] for k in z.files}
    tree = unflatten_tree(flat)
    return tree.get("params", {}), tree.get("batch_stats", {})


def load_backbone_variables(
    variables: Dict,
    path: str,
    backbone: str = "backbone",
    dtype: Optional[Any] = None,
) -> Dict:
    """Merge a canonical checkpoint into a model's initialized variables.

    ``variables`` is the output of ``model.init`` (with the backbone as
    submodule ``backbone``). Every file tensor must match an existing
    leaf (same path, same shape) and every backbone leaf must be
    covered — asymmetries raise with the offending paths listed.
    Returns a NEW variables dict; the head is untouched.
    """
    import jax

    p_new, bs_new = load_backbone_npz(path)
    loaded = flatten_tree({"params": p_new, "batch_stats": bs_new})

    target = flatten_tree(
        {
            "params": variables["params"].get(backbone, {}),
            "batch_stats": variables.get("batch_stats", {}).get(backbone, {}),
        }
    )
    missing = sorted(set(target) - set(loaded))
    unexpected = sorted(set(loaded) - set(target))
    if missing or unexpected:
        raise ValueError(
            f"checkpoint {path!r} does not cover the backbone: "
            f"missing={missing[:8]}{'...' if len(missing) > 8 else ''} "
            f"unexpected={unexpected[:8]}{'...' if len(unexpected) > 8 else ''} "
            f"(width_mult mismatch?)"
        )
    bad = [
        k for k in target if tuple(loaded[k].shape) != tuple(target[k].shape)
    ]
    if bad:
        detail = ", ".join(
            f"{k}: file{loaded[k].shape} != model{target[k].shape}"
            for k in bad[:8]
        )
        raise ValueError(f"checkpoint shape mismatch: {detail}")

    def cast(x, like):
        want = dtype or np.asarray(like).dtype
        return np.asarray(x).astype(want)

    out = jax.tree.map(lambda x: x, variables)  # shallow-ish copy
    out["params"] = dict(out["params"])
    out["params"][backbone] = jax.tree.map(
        cast, p_new, variables["params"][backbone]
    )
    if bs_new:
        out["batch_stats"] = dict(out.get("batch_stats", {}))
        out["batch_stats"][backbone] = jax.tree.map(
            cast, bs_new, variables["batch_stats"][backbone]
        )
    return out


# ---------------------------------------------------------------------------
# converters (run offline where the source files exist)
# ---------------------------------------------------------------------------


def convert_torchvision_state_dict(sd: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """torchvision ``mobilenet_v2`` state_dict → canonical flat dict.

    Layout conversions: conv (out,in,kh,kw) → (kh,kw,in,out); depthwise
    (ch,1,kh,kw) → (kh,kw,1,ch) (same transpose); BatchNorm
    weight/bias/running_mean/running_var → scale/bias/mean/var.
    """

    def arr(name):
        t = sd[name]
        return t.detach().cpu().numpy() if hasattr(t, "detach") else np.asarray(t)

    out: Dict[str, np.ndarray] = {}

    def conv_bn(dst: str, conv_key: str, bn_key: str) -> None:
        w = arr(f"{conv_key}.weight")
        out[f"params/{dst}/conv/kernel"] = np.transpose(w, (2, 3, 1, 0))
        out[f"params/{dst}/bn/scale"] = arr(f"{bn_key}.weight")
        out[f"params/{dst}/bn/bias"] = arr(f"{bn_key}.bias")
        out[f"batch_stats/{dst}/bn/mean"] = arr(f"{bn_key}.running_mean")
        out[f"batch_stats/{dst}/bn/var"] = arr(f"{bn_key}.running_var")

    conv_bn("stem", "features.0.0", "features.0.1")
    fi = 1
    for name, t, _si, _i in _block_names():
        base = f"features.{fi}"
        if t != 1:
            conv_bn(f"{name}/expand", f"{base}.conv.0.0", f"{base}.conv.0.1")
            conv_bn(f"{name}/depthwise", f"{base}.conv.1.0", f"{base}.conv.1.1")
            conv_bn(f"{name}/project", f"{base}.conv.2", f"{base}.conv.3")
        else:
            conv_bn(f"{name}/depthwise", f"{base}.conv.0.0", f"{base}.conv.0.1")
            conv_bn(f"{name}/project", f"{base}.conv.1", f"{base}.conv.2")
        fi += 1
    conv_bn("head_conv", "features.18.0", "features.18.1")
    return out


# Keras tf.keras.applications.MobileNetV2 layer names, in our block order
def _keras_layer_names():
    yield "stem", "Conv1", "bn_Conv1", None
    for name, t, si, i in _block_names():
        k = 0 if (si == 0 and i == 0) else None
        if k == 0:  # first block is named expanded_conv_* (no index)
            yield f"{name}/depthwise", "expanded_conv_depthwise", \
                "expanded_conv_depthwise_BN", "depthwise"
            yield f"{name}/project", "expanded_conv_project", \
                "expanded_conv_project_BN", None
        else:
            idx = sum(n for _t2, _c2, n in _SETTINGS[:si]) + i  # 1..16
            if t != 1:
                yield f"{name}/expand", f"block_{idx}_expand", \
                    f"block_{idx}_expand_BN", None
            yield f"{name}/depthwise", f"block_{idx}_depthwise", \
                f"block_{idx}_depthwise_BN", "depthwise"
            yield f"{name}/project", f"block_{idx}_project", \
                f"block_{idx}_project_BN", None
    yield "head_conv", "Conv_1", "Conv_1_bn", None


def convert_keras_h5(path: str) -> Dict[str, np.ndarray]:
    """Keras MobileNetV2 ``.h5`` weight file → canonical flat dict.

    Keras conv kernels are already (kh,kw,in,out); depthwise kernels
    (kh,kw,ch,1) transpose to (kh,kw,1,ch). BN order:
    gamma/beta/moving_mean/moving_variance.
    """
    import h5py

    by_layer: Dict[str, Dict[str, np.ndarray]] = {}
    with h5py.File(path, "r") as f:
        root = f["model_weights"] if "model_weights" in f else f

        def visit(name, obj):
            if isinstance(obj, h5py.Dataset):
                parts = [p for p in name.split("/") if p]
                layer, wname = parts[0], parts[-1].split(":")[0]
                by_layer.setdefault(layer, {})[wname] = np.asarray(obj)

        root.visititems(visit)

    out: Dict[str, np.ndarray] = {}
    for dst, conv_l, bn_l, kind in _keras_layer_names():
        conv_w = by_layer[conv_l]
        kname = "depthwise_kernel" if kind == "depthwise" else "kernel"
        w = conv_w[kname]
        if kind == "depthwise":
            w = np.transpose(w, (0, 1, 3, 2))
        out[f"params/{dst}/conv/kernel"] = w
        bn = by_layer[bn_l]
        out[f"params/{dst}/bn/scale"] = bn["gamma"]
        out[f"params/{dst}/bn/bias"] = bn["beta"]
        out[f"batch_stats/{dst}/bn/mean"] = bn["moving_mean"]
        out[f"batch_stats/{dst}/bn/var"] = bn["moving_variance"]
    return out


_RESNET_REPEATS = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3), 50: (3, 4, 6, 3)}


def convert_torchvision_resnet_state_dict(
    sd: Dict[str, Any], depth: int = 18
) -> Dict[str, np.ndarray]:
    """torchvision ``resnet{18,34,50}`` state_dict → canonical flat dict
    for tpuflow.models.resnet.ResNet (same layout rules as the
    MobileNetV2 converter; torchvision resnet key grammar:
    ``conv1/bn1``, ``layer{1..4}.{b}.conv{1..3}/bn{1..3}``,
    ``layer{L}.0.downsample.{0,1}``; the classifier ``fc.*`` and BN
    ``num_batches_tracked`` bookkeeping are skipped — the backbone is
    the ``include_top=False`` form)."""
    if depth not in _RESNET_REPEATS:
        raise ValueError(f"depth must be one of {sorted(_RESNET_REPEATS)}")

    def arr(name):
        t = sd[name]
        return t.detach().cpu().numpy() if hasattr(t, "detach") else np.asarray(t)

    out: Dict[str, np.ndarray] = {}

    def conv_bn(dst: str, conv_key: str, bn_key: str) -> None:
        out[f"params/{dst}/conv/kernel"] = np.transpose(
            arr(f"{conv_key}.weight"), (2, 3, 1, 0)
        )
        out[f"params/{dst}/bn/scale"] = arr(f"{bn_key}.weight")
        out[f"params/{dst}/bn/bias"] = arr(f"{bn_key}.bias")
        out[f"batch_stats/{dst}/bn/mean"] = arr(f"{bn_key}.running_mean")
        out[f"batch_stats/{dst}/bn/var"] = arr(f"{bn_key}.running_var")

    conv_bn("stem", "conv1", "bn1")
    n_convs = 2 if depth in (18, 34) else 3
    for si, n_blocks in enumerate(_RESNET_REPEATS[depth]):
        for bi in range(n_blocks):
            base = f"layer{si + 1}.{bi}"
            dst = f"stage{si}_block{bi}"
            for ci in range(1, n_convs + 1):
                conv_bn(f"{dst}/conv{ci}", f"{base}.conv{ci}",
                        f"{base}.bn{ci}")
            if f"{base}.downsample.0.weight" in sd:
                conv_bn(f"{dst}/down", f"{base}.downsample.0",
                        f"{base}.downsample.1")
    return out


def convert(src: str, dst: str) -> None:
    """Convert a torchvision ``.pth``/``.pt`` (MobileNetV2 or
    ResNet-18/34/50, auto-detected from the key grammar) or Keras
    ``.h5`` MobileNetV2 checkpoint into the canonical npz at ``dst``."""
    if src.endswith((".h5", ".hdf5")):
        flat = convert_keras_h5(src)
    else:
        import torch

        obj = torch.load(src, map_location="cpu", weights_only=True)
        sd = obj.get("state_dict", obj) if isinstance(obj, dict) else obj
        if "layer1.0.conv1.weight" in sd:  # torchvision resnet grammar
            counts = tuple(
                len({k.split(".")[1] for k in sd
                     if k.startswith(f"layer{i}.")})
                for i in (1, 2, 3, 4)
            )
            has_conv3 = "layer1.0.conv3.weight" in sd
            by_sig = {((2, 2, 2, 2), False): 18, ((3, 4, 6, 3), False): 34,
                      ((3, 4, 6, 3), True): 50}
            depth = by_sig.get((counts, has_conv3))
            if depth is None:
                # e.g. resnet101 (3,4,23,3): all resnet50 keys EXIST, so
                # a prefix conversion would silently drop blocks — fail
                # loudly instead
                raise ValueError(
                    f"unsupported torchvision resnet variant: stage "
                    f"block counts {counts}, bottleneck={has_conv3} "
                    "(supported: resnet18/34/50)"
                )
            flat = convert_torchvision_resnet_state_dict(sd, depth)
        else:
            flat = convert_torchvision_state_dict(sd)
    np.savez(dst, **flat)


if __name__ == "__main__":
    if len(sys.argv) != 3:
        print(
            "usage: python -m tpuflow.models.pretrained "
            "<mobilenet_v2.{pth,h5}> <out.npz>",
            file=sys.stderr,
        )
        raise SystemExit(2)
    convert(sys.argv[1], sys.argv[2])
    print(f"wrote {sys.argv[2]}")
