"""ViT-prefix VLM: images as prompt-prefix tokens (ISSUE 18).

The serving substrate moves int32 token chains — packing, ``pad_lens``
masking, KV pages, chunk-keyed prefix caching, the tier hierarchy, the
page wire. A vision-language workload rides ALL of it unchanged by
making the image itself an int32 chain:

- :func:`patchify` splits an (H, W, C) image into the ViT's
  non-overlapping ``patch×patch`` grid (Dosovitskiy et al., "An Image
  is Worth 16x16 Words" — PAPERS.md), flattened per patch;
- :func:`image_to_tokens` maps each patch to ONE id in the model's
  image vocabulary via a FROZEN quantize-then-hash codebook assignment
  (uint8 quantization → blake2b → ``% image_vocab``). No learned
  encoder runs on the host and no RNG is involved, so the mapping is
  deterministic across processes and time: the same image always
  yields the same chain, which is exactly what makes image prefixes
  prefix-CACHEABLE — ``chunk_keys`` over identical chains collide, so
  a shared image's KV pages hit in the radix tree, stay warm in the
  PR 16 host/disk tiers, and dedup on the PR 14 wire, all for free;
- the model side is ``TransformerLM(image_vocab=N)``: ids in
  ``[vocab_size, vocab_size + image_vocab)`` gather from a separate
  learned ``img_embed`` table (the patch embedding, trained end to
  end through the LM), while the LM head stays text-vocab-wide so
  image ids can never be SAMPLED — images are prompts, not outputs.

What this is not: a full ViT tower in the prompt path. The codebook
assignment is a discrete bottleneck (VQ-style, frozen rather than
learned); ``models/vit.py`` remains the continuous-patch classifier.
The trade is deliberate — a continuous vision encoder would make
image prefixes unkeyable floats and fork the entire serving substrate,
where the codebook keeps one engine serving both modalities.
"""

from __future__ import annotations

import hashlib
from typing import Any, Optional, Sequence

import numpy as np

from tpuflow.models.transformer import TransformerLM, build_transformer_lm


def patchify(image: np.ndarray, patch: int) -> np.ndarray:
    """(H, W, C) → (n_patches, patch*patch*C), row-major grid order —
    the ViT patch grid as flat vectors. H and W must be multiples of
    ``patch`` (same rule :func:`~tpuflow.models.vit.build_vit`
    enforces)."""
    img = np.asarray(image)
    if img.ndim == 2:
        img = img[:, :, None]
    if img.ndim != 3:
        raise ValueError(
            f"image must be (H, W) or (H, W, C), got shape "
            f"{tuple(np.shape(image))}"
        )
    h, w, c = img.shape
    if h % patch or w % patch:
        raise ValueError(
            f"image size {h}x{w} must be a multiple of patch_size "
            f"({patch}) — non-overlapping grid, no padding"
        )
    gh, gw = h // patch, w // patch
    grid = img.reshape(gh, patch, gw, patch, c)
    return grid.transpose(0, 2, 1, 3, 4).reshape(gh * gw, patch * patch * c)


def _quantize_patch(p: np.ndarray) -> np.ndarray:
    """Frozen uint8 quantizer: float images (any range clipped to
    [0, 1]) and uint8 images land on the SAME byte representation —
    the determinism anchor for the hash."""
    if np.issubdtype(p.dtype, np.floating):
        return np.clip(np.asarray(p, np.float64) * 255.0 + 0.5,
                       0, 255).astype(np.uint8)
    return np.asarray(p).astype(np.uint8)


def image_to_tokens(image: np.ndarray, *, patch: int, image_vocab: int,
                    text_vocab: int) -> np.ndarray:
    """Deterministic image → int32 prompt-prefix chain.

    Each patch quantizes to uint8 and hashes (blake2b, 8 bytes) into
    one codebook id; the returned ids live in ``[text_vocab,
    text_vocab + image_vocab)`` — the ``img_embed`` range of a
    ``TransformerLM(image_vocab=...)``. Host-only numpy: callers
    prepend the result to their text ids and submit like any prompt.
    Identical images (bit-identical after quantization) produce
    identical chains — the property every downstream cache keys on."""
    if image_vocab < 1:
        raise ValueError(
            f"image_vocab must be >= 1 to tokenize images, got "
            f"{image_vocab}"
        )
    patches = patchify(image, patch)
    toks = np.empty((patches.shape[0],), np.int32)
    for i, p in enumerate(patches):
        digest = hashlib.blake2b(
            _quantize_patch(p).tobytes(), digest_size=8).digest()
        toks[i] = text_vocab + int.from_bytes(digest, "little") % image_vocab
    return toks


def vlm_prompt(image: Optional[np.ndarray], text_ids: Sequence[int], *,
               patch: int, image_vocab: int,
               text_vocab: int) -> np.ndarray:
    """Image-prefix ++ text ids as one int32 prompt (image optional —
    text-only requests pass ``None`` and interleave in the same
    batch). The image goes FIRST so shared images share a chain
    PREFIX — the unit of prefix-cache reuse."""
    text = np.asarray(list(text_ids), np.int32)
    if image is None:
        return text
    img = image_to_tokens(image, patch=patch, image_vocab=image_vocab,
                          text_vocab=text_vocab)
    return np.concatenate([img, text]).astype(np.int32)


def build_vlm_lm(
    vocab_size: int = 32000,
    image_vocab: int = 1024,
    img_size: int = 224,
    patch_size: int = 16,
    **lm_kwargs: Any,
) -> TransformerLM:
    """A served VLM: :func:`build_transformer_lm` with the image-token
    table sized and the patch geometry validated up front (the
    patch-budget math a deployment sizes buckets around: one image
    costs ``(img_size // patch_size)**2`` prompt positions)."""
    if img_size < patch_size or img_size % patch_size:
        raise ValueError(
            f"img_size ({img_size}) must be a positive multiple of "
            f"patch_size ({patch_size}) — patches tile the image "
            "exactly (ViT grid)"
        )
    if image_vocab < 1:
        raise ValueError(
            f"image_vocab must be >= 1 for a VLM (it sizes the "
            f"patch-token embedding table), got {image_vocab}"
        )
    return build_transformer_lm(
        vocab_size=vocab_size, image_vocab=image_vocab, **lm_kwargs)


def n_image_tokens(img_size: int, patch_size: int) -> int:
    """Prompt positions one image consumes: the patch-grid size."""
    return (img_size // patch_size) * (img_size // patch_size)


__all__ = [
    "patchify",
    "image_to_tokens",
    "vlm_prompt",
    "build_vlm_lm",
    "n_image_tokens",
]
