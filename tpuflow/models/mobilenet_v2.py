"""MobileNetV2 in Flax — the backbone of the reference's transfer model.

The reference uses ``tf.keras.applications.MobileNetV2(include_top=False)``
(reference P1/02_model_training_single_node.py:164-169). This is a
TPU-first reimplementation, not a port: NHWC layout (TPU-native),
bfloat16 compute with float32 params/statistics, ReLU6 fused by XLA into
the surrounding convs, static shapes throughout. Architecture follows
the MobileNetV2 paper (Sandler et al. 2018): stem conv(32,s2) →
inverted-residual stages (t,c,n,s) = (1,16,1,1)(6,24,2,2)(6,32,3,2)
(6,64,4,2)(6,96,3,1)(6,160,3,2)(6,320,1,1) → conv(1280).

Weights initialize randomly by default; ``tpuflow.models.pretrained``
loads a converted ImageNet checkpoint (torchvision ``.pth`` or Keras
``.h5``, converted offline to the canonical npz) via
``build_model(weights=path)`` — the reference's transfer-learning
story (Keras default weights='imagenet', P1/02:164-169).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any

# (expand_ratio t, out_channels c, repeats n, first_stride s)
_INVERTED_RESIDUAL_SETTINGS: Sequence[Tuple[int, int, int, int]] = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


def make_divisible(v: float, divisor: int = 8) -> int:
    """Round channel counts to multiples of 8 (also MXU-friendly lanes)."""
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBN(nn.Module):
    """Conv → BatchNorm → activation, shared by the CNN backbones.

    Defaults are the MobileNetV2 conventions (SAME padding, BN
    momentum 0.999/eps 1e-3, ReLU6); ResNet overrides them
    (tpuflow/models/resnet.py). ``act_fn`` takes precedence over the
    boolean ``act`` (which selects ReLU6) when set.
    """

    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    groups: int = 1
    act: bool = True
    dtype: Dtype = jnp.bfloat16
    momentum: float = 0.999
    epsilon: float = 1e-3
    act_fn: Any = None
    padding: Any = "SAME"
    # BN folding (round-5 inference/frozen-backbone lever): the conv
    # absorbs the BN scale into its kernel and grows a bias — the BN
    # layer disappears from the graph entirely (its per-element affine
    # would otherwise survive as runtime-array multiplies XLA cannot
    # constant-fold under jit). Only valid where BN statistics are
    # frozen: inference, or the transfer classifier's frozen backbone
    # (P1/02:167-169 trainable=False semantics). Convert unfolded
    # checkpoints with ``fold_bn_params``.
    fold_bn: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.fold_bn and train:
            raise ValueError(
                "fold_bn=True is inference-only (BN statistics are "
                "folded into the conv and can no longer update); run "
                "with train=False or build with fold_bn=False"
            )
        x = nn.Conv(
            self.features,
            self.kernel,
            strides=self.strides,
            padding=self.padding,
            use_bias=self.fold_bn,
            feature_group_count=self.groups,
            dtype=self.dtype,
            name="conv",
        )(x)
        if not self.fold_bn:
            x = nn.BatchNorm(
                use_running_average=not train,
                momentum=self.momentum,
                epsilon=self.epsilon,
                dtype=self.dtype,
                name="bn",
            )(x)
        if self.act_fn is not None:
            x = self.act_fn(x)
        elif self.act:
            x = jnp.minimum(jnp.maximum(x, 0.0), 6.0)  # ReLU6
        return x


class InvertedResidual(nn.Module):
    features: int
    strides: Tuple[int, int]
    expand_ratio: int
    dtype: Dtype = jnp.bfloat16
    fold_bn: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        hidden = in_ch * self.expand_ratio
        y = x
        if self.expand_ratio != 1:
            y = ConvBN(hidden, (1, 1), act=True, dtype=self.dtype,
                       fold_bn=self.fold_bn, name="expand")(
                y, train
            )
        y = ConvBN(
            hidden,
            (3, 3),
            strides=self.strides,
            groups=hidden,
            act=True,
            dtype=self.dtype,
            fold_bn=self.fold_bn,
            name="depthwise",
        )(y, train)
        y = ConvBN(self.features, (1, 1), act=False, dtype=self.dtype,
                   fold_bn=self.fold_bn, name="project")(
            y, train
        )
        if self.strides == (1, 1) and in_ch == self.features:
            y = x + y
        return y


class MobileNetV2(nn.Module):
    """Feature extractor (``include_top=False`` form).

    Output: [N, H/32, W/32, 1280·width] feature map. Inputs are expected
    preprocessed to [-1, 1] (tpuflow.models.preprocess).
    """

    width_mult: float = 1.0
    dtype: Dtype = jnp.bfloat16
    fold_bn: bool = False  # see ConvBN.fold_bn (inference-only)

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        stem = make_divisible(32 * self.width_mult)
        x = ConvBN(stem, (3, 3), strides=(2, 2), dtype=self.dtype,
                   fold_bn=self.fold_bn, name="stem")(
            x, train
        )
        for si, (t, c, n, s) in enumerate(_INVERTED_RESIDUAL_SETTINGS):
            out_ch = make_divisible(c * self.width_mult)
            for i in range(n):
                x = InvertedResidual(
                    out_ch,
                    strides=(s, s) if i == 0 else (1, 1),
                    expand_ratio=t,
                    dtype=self.dtype,
                    fold_bn=self.fold_bn,
                    name=f"block_{si}_{i}",
                )(x, train)
        last = make_divisible(1280 * max(1.0, self.width_mult))
        x = ConvBN(last, (1, 1), dtype=self.dtype, fold_bn=self.fold_bn,
                   name="head_conv")(x, train)
        return x


def fold_bn_params(params, batch_stats, eps: float):
    """Fold frozen BatchNorm layers into their preceding convs.

    Walks an UNFOLDED backbone's ``params``/``batch_stats`` trees and,
    at every ConvBN node (a dict holding both ``conv`` and ``bn``),
    rewrites the conv for the ``fold_bn=True`` module structure::

        s      = gamma / sqrt(var + eps)          # per out-channel
        W'     = W * s          (broadcast on the out-channel axis —
                                 last kernel axis, grouped convs
                                 included)
        bias'  = beta - s * mean

    so ``conv(x, W') + bias' == BN(conv(x, W))`` exactly (inference
    BN). Returns a NEW params tree with every ``bn`` subtree removed
    and conv biases added — load it into a ``fold_bn=True`` model;
    ``batch_stats`` is consumed entirely. ``eps`` must match the
    module convention (MobileNetV2 1e-3, ResNet 1e-5).
    """
    def walk(p, bs):
        if not isinstance(p, dict):
            return p
        out = {}
        for key, sub in p.items():
            if (
                key == "bn"
                and "conv" in p
                and isinstance(sub, dict)
                and isinstance(bs, dict)
                and "bn" in bs
            ):
                continue  # consumed by the sibling conv below
            if (
                key == "conv"
                and "bn" in p
                and isinstance(bs, dict)
                and "bn" in bs
            ):
                gamma = p["bn"]["scale"].astype(jnp.float32)
                beta = p["bn"]["bias"].astype(jnp.float32)
                mean = bs["bn"]["mean"].astype(jnp.float32)
                var = bs["bn"]["var"].astype(jnp.float32)
                s = gamma / jnp.sqrt(var + eps)
                kern = sub["kernel"]
                out[key] = {
                    "kernel": (kern.astype(jnp.float32) * s).astype(
                        kern.dtype
                    ),
                    "bias": (beta - s * mean).astype(kern.dtype),
                }
            else:
                out[key] = walk(
                    sub, bs.get(key) if isinstance(bs, dict) else None
                )
        return out

    return walk(params, batch_stats)
