"""Input preprocessing (C7).

≙ the reference's ``preprocess(content, label)``: decode_jpeg → resize →
``mobilenet_v2.preprocess_input`` (scale to [-1, 1])
(P1/02_model_training_single_node.py:119-126). In the TPU build the
decode+resize live in the native host plane (tpuflow.native); only the
scaling runs on device so the host→device transfer stays uint8 (4x less
HBM/PCIe traffic) and XLA fuses the scale into the first conv.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def preprocess_input(x: jnp.ndarray, dtype=jnp.bfloat16) -> jnp.ndarray:
    """uint8 [0,255] → dtype [-1,1] (≙ keras mobilenet_v2.preprocess_input)."""
    return (x.astype(dtype) / jnp.asarray(127.5, dtype)) - jnp.asarray(1.0, dtype)


def random_flip(x: jnp.ndarray, rng) -> jnp.ndarray:
    """Per-sample random horizontal flip, on device (BEYOND-REFERENCE:
    the workshop trains with no augmentation at all, P1/02:119-126).

    ``x``: (B, H, W, C); ``rng``: a jax PRNG key (fold the step counter
    in upstream). A (B,1,1,1) bernoulli mask selects flipped rows —
    pure vectorized ops, so XLA fuses it into the input pipeline with
    no host round-trip and no data-dependent control flow."""
    mask = jax.random.bernoulli(rng, 0.5, (x.shape[0], 1, 1, 1))
    return jnp.where(mask, x[:, :, ::-1, :], x)


def preprocess(content: bytes, img_height: int = 224, img_width: int = 224) -> np.ndarray:
    """Host-side single-image path: JPEG bytes → float32 [-1,1] HWC.

    The per-example convenience form (used by packaged inference models);
    batch training uses the native batched plane directly.
    """
    from tpuflow.native import decode_resize_batch

    imgs, ok = decode_resize_batch([content], img_height, img_width, num_threads=1)
    if not ok[0]:
        raise ValueError("corrupt image")
    return imgs[0].astype(np.float32) / 127.5 - 1.0
