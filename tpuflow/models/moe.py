"""Mixture-of-Experts layer with expert parallelism.

Absent from the reference (SURVEY.md §2c lists expert parallelism as an
honest absence); first-class here. GShard/Mixtral-style top-k routed
MoE in the TPU-idiomatic GSPMD formulation:

- expert weights are stacked ``(E, ...)`` and annotated over an
  ``expert`` mesh axis via ``nn.with_partitioning``; under ``jit`` on a
  mesh with that axis, XLA partitions the batched expert matmuls and
  inserts the dispatch/combine **all-to-alls** itself — the same
  compiler-scheduled path the framework uses for TP (no hand-written
  collectives, they ride ICI);
- routing is dense one-hot dispatch with a per-expert CAPACITY: each
  token's top-k experts get softmax gates, tokens beyond an expert's
  capacity are dropped (gate 0) — keeping every shape static for XLA
  (data-dependent gather/scatter would forbid MXU tiling);
- the standard load-balance auxiliary loss (mean gate fraction ×
  routed fraction per expert, summed over experts and scaled by E·α)
  is returned alongside the output so the caller can add it to the
  task loss.

``no_drop=True`` switches to DROPLESS routing (ISSUE 18, serving):
every token keeps its renormalized top-k gates and every expert runs
on every token (dense dispatch, gates zero the unrouted terms). There
is no cumsum position and no capacity, so each token's output is a
pure function of ITS OWN hidden state — the property paged serving
needs for token identity, where a request's batch neighbors change
segment to segment (capacity drops would make outputs depend on
co-scheduled traffic). The capacity trade-off moves to the HOST: the
scheduler's admission gate (``moe_capacity_factor``) throttles new
work when an expert runs hot instead of dropping tokens mid-batch.

Both modes sow per-expert routed-token counts into the ``"moe"``
collection (shape (B, S, E) one-hot assignment mass) when the caller
marks it mutable — the serve engine's per-expert load harvest; a
no-op under the training ``mutable=['losses']`` convention.

Use ``ep_axis=None`` (default) for replicated experts (single device /
DP); ``ep_axis='expert'`` when the mesh carries an expert axis.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

from tpuflow.models._layers import dense_init as _dense_init  # noqa: E402
from tpuflow.models._layers import part as _part  # noqa: E402

EXPERT_AXIS = "expert"


class MoEMlp(nn.Module):
    """Top-k routed expert MLP: (B, S, dim) → ((B, S, dim), aux_loss)."""

    dim: int
    hidden: int
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    dtype: Any = jnp.bfloat16
    ep_axis: Optional[str] = None  # mesh axis sharding the expert dim
    # dropless routing (serving): no capacity, every token keeps its
    # renormalized top-k gates — batch-composition-independent outputs
    no_drop: bool = False

    @nn.compact
    def __call__(self, x) -> Tuple[jnp.ndarray, jnp.ndarray]:
        b, s, d = x.shape
        e, k = self.n_experts, self.top_k
        t = b * s
        # per-expert capacity: even share × factor × k (each token asks
        # for k slots), at least 1 — static for XLA
        cap = max(1, int(self.capacity_factor * k * t / e))
        ep = self.ep_axis is not None

        tokens = x.reshape(t, d)
        # router in float32 (small, precision-sensitive)
        router_logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, name="router"
        )(tokens.astype(jnp.float32))
        probs = nn.softmax(router_logits, axis=-1)  # (T, E)

        # top-k one-hot dispatch masks, built greedily so a token's k
        # choices occupy distinct experts
        gates = jnp.zeros((t, e), jnp.float32)
        mask = jnp.zeros((t, e), jnp.float32)
        remaining = probs
        for _ in range(k):
            choice = jnp.argmax(remaining, axis=-1)
            one_hot = nn.one_hot(choice, e, dtype=jnp.float32)
            gates = gates + one_hot * probs
            mask = mask + one_hot
            remaining = remaining * (1.0 - one_hot)

        # per-expert routed-token load, sown for the serve engine's
        # harvest (mutable=['moe']); a silent no-op everywhere else.
        # (B, S, E) so the decode segment fn can zero finished rows
        # before reducing — the gauge counts LIVE tokens only.
        self.sow("moe", "expert_tokens", mask.reshape(b, s, e))

        w_in = self.param(
            "w_in",
            _part(_dense_init, (self.ep_axis, None, None), ep),
            (e, d, self.hidden),
            jnp.float32,
        )
        w_out = self.param(
            "w_out",
            _part(_dense_init, (self.ep_axis, None, None), ep),
            (e, self.hidden, d),
            jnp.float32,
        )

        if self.no_drop:
            # dropless: renormalize the top-k gates directly (no
            # capacity zeroing) and run EVERY expert on every token —
            # the gates zero the unrouted terms in the combine. Each
            # token's output depends only on its own hidden state, so
            # serving stays token-identical no matter which requests
            # share the batch. O(T·E·hidden) FLOPs — the dense-dispatch
            # price, paid at decode batch sizes (slots × 1 token).
            denom = jnp.sum(gates, axis=-1, keepdims=True)
            gates_n = gates / jnp.maximum(denom, 1e-9)
            h = nn.silu(jnp.einsum(
                "td,edh->teh", tokens.astype(self.dtype),
                w_in.astype(self.dtype)))
            expert_out = jnp.einsum(
                "teh,ehd->ted", h, w_out.astype(self.dtype))
            out = jnp.einsum(
                "te,ted->td", gates_n, expert_out.astype(jnp.float32))
        else:
            # position of each token within its expert's buffer (per
            # expert running count over tokens); tokens past capacity
            # are dropped
            position = jnp.cumsum(mask, axis=0) * mask - 1.0  # (T, E)
            in_cap = (position < cap) & (mask > 0)
            gates = jnp.where(in_cap, gates, 0.0)
            # renormalize surviving gates so each token's weights sum
            # to 1
            denom = jnp.sum(gates, axis=-1, keepdims=True)
            gates = gates / jnp.maximum(denom, 1e-9)

            # (T, E, C) one-hot of (expert, slot) per token
            pos_idx = jnp.clip(position, 0, cap - 1).astype(jnp.int32)
            slot_one_hot = nn.one_hot(
                pos_idx, cap, dtype=jnp.float32)  # (T,E,C)
            dispatch = slot_one_hot * in_cap[..., None]  # (T, E, C)

            # dispatch tokens → (E, C, d); under GSPMD with expert-
            # sharded weights XLA turns this into the dispatch
            # all-to-all
            expert_in = jnp.einsum(
                "tec,td->ecd", dispatch, tokens.astype(jnp.float32)
            ).astype(self.dtype)
            h = nn.silu(jnp.einsum(
                "ecd,edh->ech", expert_in, w_in.astype(self.dtype)))
            expert_out = jnp.einsum(
                "ech,ehd->ecd", h, w_out.astype(self.dtype))

            # combine back with gate weights (the combine all-to-all)
            combine = dispatch * gates[..., None]  # (T, E, C)
            out = jnp.einsum(
                "tec,ecd->td", combine, expert_out.astype(jnp.float32)
            )

        # load-balance aux loss (Switch/GShard): E · Σ_e f_e · p_e where
        # f_e = fraction of tokens routed to e, p_e = mean router prob
        f = jnp.mean(mask, axis=0)  # (E,) — pre-capacity routing share
        p = jnp.mean(probs, axis=0)
        aux = self.aux_loss_weight * e * jnp.sum(f * p)

        return out.astype(self.dtype).reshape(b, s, d), aux
