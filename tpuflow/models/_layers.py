"""Shared layer helpers for the model families (ViT, transformer, MoE).

One home for the tensor-parallel annotation idiom so a change to the
partitioning-metadata API lands in every model family at once.
"""

from __future__ import annotations

import flax.linen as nn

dense_init = nn.initializers.xavier_uniform()


def part(init, names, enabled: bool = True):
    """TP annotation via ``nn.with_partitioning``, disabled in manual
    (shard_map) sequence-parallel mode: flax re-applies partitioning
    metadata as sharding constraints at apply time, which would
    reference the absent mesh axes there (params are replicated by the
    shard_map in_spec instead)."""
    return nn.with_partitioning(init, names) if enabled else init
