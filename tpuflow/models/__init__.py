from tpuflow.models.mobilenet_v2 import MobileNetV2  # noqa: F401
from tpuflow.models.resnet import ResNet, build_resnet  # noqa: F401
from tpuflow.models.classifier import (  # noqa: F401
    TransferClassifier,
    build_model,
    backbone_param_mask,
)
from tpuflow.models.preprocess import preprocess_input, preprocess  # noqa: F401
from tpuflow.models.pretrained import (  # noqa: F401
    load_backbone_npz,
    load_backbone_variables,
    save_backbone_npz,
)
from tpuflow.models.vit import ViTClassifier, build_vit  # noqa: F401
from tpuflow.models.vlm import (  # noqa: F401
    build_vlm_lm,
    image_to_tokens,
    n_image_tokens,
    patchify,
    vlm_prompt,
)
from tpuflow.models.transformer import (  # noqa: F401
    TransformerLM,
    build_transformer_lm,
    draft_lm_config,
    next_token_loss,
    share_draft_embeddings,
)
