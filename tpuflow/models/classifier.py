"""Transfer-learning classifier (C6): frozen backbone + trainable head.

≙ the reference's ``build_model``: MobileNetV2(include_top=False) with
every backbone layer frozen, then GlobalAveragePooling2D → Dropout(p) →
Dense(num_classes) producing LOGITS (loss uses from_logits=True)
(P1/02_model_training_single_node.py:159-178; HPO variant with dropout
param P2/01:92-108).

Freezing semantics match Keras ``trainable=False`` exactly: frozen
backbone params get zero updates (optax mask, see
``backbone_param_mask``) AND backbone BatchNorm runs in inference mode
so running statistics never update (P1/02:167-169) — the subtle part
called out in SURVEY.md §7 "hard parts".
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import flax.linen as nn
import jax.numpy as jnp

from tpuflow.models.mobilenet_v2 import MobileNetV2
from tpuflow.models.resnet import ResNet

BACKBONE = "backbone"

# the supported backbones and their BN epsilon conventions — ONE list
# for both model construction and checkpoint folding (eps is
# numerics-critical: folding with the wrong convention silently skews
# small-variance channels by ~sqrt(eps_a/eps_b))
BACKBONE_BN_EPS = {
    "mobilenet_v2": 1e-3,  # Keras/MobileNet convention
    "resnet18": 1e-5,  # torch convention
    "resnet34": 1e-5,
    "resnet50": 1e-5,
}


class TransferClassifier(nn.Module):
    num_classes: int = 5
    dropout: float = 0.5
    width_mult: float = 1.0
    freeze_backbone: bool = True
    dtype: Any = jnp.bfloat16
    # path to a converted backbone checkpoint (models/pretrained.py
    # canonical npz); applied by Trainer.init_state after module init —
    # ≙ the Keras default weights='imagenet' (P1/02:164-169)
    weights: Optional[str] = None
    # 'mobilenet_v2' (reference parity) | 'resnet18' | 'resnet34' |
    # 'resnet50' — every backbone shares the freeze/pretrained/trainer
    # machinery (params live under the BACKBONE subtree)
    backbone: str = "mobilenet_v2"
    # fold the frozen backbone's BatchNorms into their convs (the BN
    # layers vanish from the graph — see mobilenet_v2.ConvBN.fold_bn).
    # Requires freeze_backbone=True: folded statistics cannot update.
    # Convert unfolded checkpoints with ``fold_backbone_variables``.
    fold_bn: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.fold_bn and not self.freeze_backbone:
            raise ValueError(
                "fold_bn=True requires freeze_backbone=True — folded BN "
                "statistics are constants baked into the conv weights"
            )
        if self.fold_bn and self.weights is not None:
            raise ValueError(
                "fold_bn=True cannot load an UNFOLDED checkpoint via "
                "weights= (the folded model has no bn leaves to fill); "
                "load into a fold_bn=False twin, convert with "
                "fold_backbone_variables, and apply the result"
            )
        # Frozen backbone always runs with train=False: BN uses running
        # averages and batch_stats stay immutable (Keras trainable=False).
        bb_train = train and not self.freeze_backbone
        if self.backbone == "mobilenet_v2":
            bb = MobileNetV2(self.width_mult, dtype=self.dtype,
                             fold_bn=self.fold_bn, name=BACKBONE)
        elif self.backbone in BACKBONE_BN_EPS:
            bb = ResNet(int(self.backbone[len("resnet"):]), dtype=self.dtype,
                        fold_bn=self.fold_bn, name=BACKBONE)
        else:
            raise ValueError(
                f"unknown backbone {self.backbone!r}; expected one of "
                f"{sorted(BACKBONE_BN_EPS)}"
            )
        feats = bb(x, train=bb_train)
        x = jnp.mean(feats, axis=(1, 2))  # GlobalAveragePooling2D
        x = nn.Dropout(self.dropout, name="head_dropout")(
            x, deterministic=not train
        )
        # Head in float32: the single small matmul costs nothing and the
        # logits/loss stay numerically clean.
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head_dense")(
            x.astype(jnp.float32)
        )
        return x  # logits


def build_model(
    img_height: int = 224,
    img_width: int = 224,
    img_channels: int = 3,
    num_classes: int = 5,
    dropout: float = 0.5,
    width_mult: float = 1.0,
    freeze_backbone: bool = True,
    dtype: Any = jnp.bfloat16,
    weights: Optional[str] = None,
    backbone: str = "mobilenet_v2",
    fold_bn: bool = False,
) -> TransferClassifier:
    """≙ build_model(img_height, img_width, img_channels, num_classes)
    (P1/02:159-178). Image size/channels are carried by the data, not the
    module (Flax modules are shape-polymorphic until init).

    ``weights``: path to a converted pretrained-backbone checkpoint
    (``tpuflow.models.pretrained`` canonical npz) — the ImageNet
    transfer-learning story (Keras ships weights='imagenet' by default,
    P1/02:164-169). The backbone loads from the file at init; the head
    always initializes fresh.
    """
    del img_height, img_width, img_channels  # API parity; shapes from data
    return TransferClassifier(
        num_classes=num_classes,
        dropout=dropout,
        width_mult=width_mult,
        freeze_backbone=freeze_backbone,
        dtype=dtype,
        weights=weights,
        backbone=backbone,
        fold_bn=fold_bn,
    )


def fold_backbone_variables(variables: Dict, backbone: str = "mobilenet_v2",
                            ) -> Dict:
    """Convert an UNFOLDED classifier's variables for a ``fold_bn=True``
    twin: the backbone subtree's BN layers fold into their convs
    (``mobilenet_v2.fold_bn_params``, eps by backbone convention:
    MobileNetV2 1e-3, ResNet 1e-5), the head passes through, and the
    backbone's ``batch_stats`` are consumed. Use when applying a real
    pretrained checkpoint to a folded model::

        vars_folded = fold_backbone_variables(vars_unfolded)
        folded.apply(vars_folded, x)  # == unfolded.apply(..., train=False)
    """
    from tpuflow.models.mobilenet_v2 import fold_bn_params

    eps = BACKBONE_BN_EPS.get(backbone)
    if eps is None:
        # eps selection is numerics-critical (a wrong convention folds
        # silently-wrong weights for small running vars) — never guess
        raise ValueError(
            f"unknown backbone {backbone!r}; expected one of "
            f"{sorted(BACKBONE_BN_EPS)} (BN eps conventions differ)"
        )
    params = dict(variables["params"])
    stats = variables.get("batch_stats", {})
    if not stats.get(BACKBONE):
        raise ValueError(
            "variables carry no backbone batch_stats to fold — already "
            "folded, or stripped? fold_backbone_variables needs the "
            "UNFOLDED model's full variables (params + batch_stats)"
        )
    params[BACKBONE] = fold_bn_params(
        params[BACKBONE], stats.get(BACKBONE, {}), eps
    )
    out = {k: v for k, v in variables.items() if k != "batch_stats"}
    out["params"] = params
    rest_stats = {k: v for k, v in stats.items() if k != BACKBONE}
    if rest_stats:
        out["batch_stats"] = rest_stats
    return out


def backbone_param_mask(params: Dict) -> Dict:
    """Pytree mask: True where params are TRAINABLE (head), False where
    frozen (backbone). Feed to ``optax.masked`` / multi_transform."""
    import jax

    def mark(path, _leaf):
        return not (len(path) > 0 and path[0].key == BACKBONE)

    return jax.tree_util.tree_map_with_path(mark, params)


def stop_gradient_frozen(params: Dict, mask: Optional[Dict]) -> Dict:
    """Sever the differentiable path into frozen (mask=False) leaves.

    Used inside trainer loss functions so autodiff never builds the
    backward graph through a frozen backbone — masking only at the
    optimizer (≙ Keras layer.trainable=False, P1/02:164-169) would
    still pay the full backprop FLOPs for gradients it then discards.
    """
    import jax

    if mask is None:
        return params
    return jax.tree_util.tree_map(
        lambda p, m: p if m else jax.lax.stop_gradient(p), params, mask
    )
