"""ResNet family in Flax — a second CNN backbone beyond the reference.

The reference's only model is MobileNetV2
(P1/02_model_training_single_node.py:164-169); tpuflow adds ResNet-18/
34/50 as drop-in backbones for the same transfer-learning classifier
(``build_model(backbone='resnet50')``), sharing the freeze semantics,
trainers, and packaging unchanged.

TPU-first choices mirror mobilenet_v2.py: NHWC layout, bfloat16 compute
with float32 parameters/BN statistics, ReLU left to XLA fusion, static
shapes. Architecture follows He et al. 2015 (v1.5 variant: stride in
the 3x3 of the bottleneck, as torchvision ships), with EXPLICIT
symmetric padding (k//2 per side) matching torch's conv convention —
XLA's 'SAME' pads stride-2 convs asymmetrically, which would shift
features relative to weights converted from torchvision. (A
torchvision→npz converter is not bundled yet; the canonical-npz merge
in models/pretrained.py is path-based and architecture-agnostic.)
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp

from tpuflow.models.mobilenet_v2 import ConvBN

Dtype = Any

# depth → (block type, stage repeats)
_CONFIGS = {
    18: ("basic", (2, 2, 2, 2)),
    34: ("basic", (3, 4, 6, 3)),
    50: ("bottleneck", (3, 4, 6, 3)),
}
_STAGE_WIDTHS = (64, 128, 256, 512)


def _cbn(features, kernel=(3, 3), strides=(1, 1), act=True,
         dtype=jnp.bfloat16, fold_bn=False, name=None):
    """ResNet-convention ConvBN: BN momentum 0.9 / eps 1e-5 (torch
    defaults), plain ReLU, symmetric k//2 padding."""
    k = kernel[0]
    return ConvBN(
        features,
        kernel,
        strides=strides,
        act=False,
        act_fn=nn.relu if act else None,
        dtype=dtype,
        momentum=0.9,
        epsilon=1e-5,
        padding=((k // 2, k // 2), (k // 2, k // 2)),
        fold_bn=fold_bn,
        name=name,
    )


class BasicBlock(nn.Module):
    features: int
    strides: Tuple[int, int]
    dtype: Dtype = jnp.bfloat16
    fold_bn: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = _cbn(self.features, (3, 3), self.strides, dtype=self.dtype,
                 fold_bn=self.fold_bn, name="conv1")(x, train)
        y = _cbn(self.features, (3, 3), act=False, dtype=self.dtype,
                 fold_bn=self.fold_bn, name="conv2")(y, train)
        if self.strides != (1, 1) or x.shape[-1] != self.features:
            x = _cbn(self.features, (1, 1), self.strides, act=False,
                     dtype=self.dtype, fold_bn=self.fold_bn,
                     name="down")(x, train)
        return nn.relu(x + y)


class Bottleneck(nn.Module):
    features: int  # output width (4x the inner width)
    strides: Tuple[int, int]
    dtype: Dtype = jnp.bfloat16
    fold_bn: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        inner = self.features // 4
        y = _cbn(inner, (1, 1), dtype=self.dtype,
                 fold_bn=self.fold_bn, name="conv1")(x, train)
        # v1.5: stride lives on the 3x3 (torchvision), not the first 1x1
        y = _cbn(inner, (3, 3), self.strides, dtype=self.dtype,
                 fold_bn=self.fold_bn, name="conv2")(y, train)
        y = _cbn(self.features, (1, 1), act=False, dtype=self.dtype,
                 fold_bn=self.fold_bn, name="conv3")(y, train)
        if self.strides != (1, 1) or x.shape[-1] != self.features:
            x = _cbn(self.features, (1, 1), self.strides, act=False,
                     dtype=self.dtype, fold_bn=self.fold_bn,
                     name="down")(x, train)
        return nn.relu(x + y)


class ResNet(nn.Module):
    """Feature extractor (``include_top=False`` form).

    Output: [N, H/32, W/32, C_last] feature map (C_last = 512 for
    18/34, 2048 for 50). Inputs preprocessed to [-1, 1]
    (tpuflow.models.preprocess) — same contract as MobileNetV2.
    """

    depth: int = 50
    dtype: Dtype = jnp.bfloat16
    fold_bn: bool = False  # see ConvBN.fold_bn (inference-only)

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.depth not in _CONFIGS:
            raise ValueError(
                f"depth must be one of {sorted(_CONFIGS)}, got {self.depth}"
            )
        kind, repeats = _CONFIGS[self.depth]
        block = BasicBlock if kind == "basic" else Bottleneck
        expansion = 1 if kind == "basic" else 4

        x = x.astype(self.dtype)
        x = _cbn(64, (7, 7), strides=(2, 2), dtype=self.dtype,
                 fold_bn=self.fold_bn, name="stem")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for si, (w, n) in enumerate(zip(_STAGE_WIDTHS, repeats)):
            for bi in range(n):
                strides = (2, 2) if (si > 0 and bi == 0) else (1, 1)
                x = block(
                    w * expansion,
                    strides=strides,
                    dtype=self.dtype,
                    fold_bn=self.fold_bn,
                    name=f"stage{si}_block{bi}",
                )(x, train)
        return x


def build_resnet(depth: int = 50, dtype: Dtype = jnp.bfloat16) -> ResNet:
    return ResNet(depth=depth, dtype=dtype)
