// tpuflow native data plane: batched JPEG decode + bilinear resize (N4/N5).
//
// The reference delegates image decode to TensorFlow's C++ kernels
// (tf.image.decode_jpeg/resize, reference P1/02_model_training_single_node.py:123-124)
// and, in the packaged-model path, to a per-row Python/PIL loop
// (P2/03_pyfunc_distributed_inference.py:204) — the documented throughput
// cliff. This library is the TPU build's native equivalent: libjpeg
// decode with DCT-domain prescaling, exact bilinear resize to the target
// resolution, and a std::thread worker pool that processes a whole batch
// into one preallocated contiguous buffer (ready for device_put).
//
// C ABI only — bound from Python with ctypes (no pybind11 in the image).

#include <cstddef>
#include <cstdio>

#include <jpeglib.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct ErrMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void on_error(j_common_ptr cinfo) {
  ErrMgr* err = reinterpret_cast<ErrMgr*>(cinfo->err);
  longjmp(err->jump, 1);
}

void on_emit(j_common_ptr, int) {}  // silence warnings

// Decode one JPEG to RGB. Uses libjpeg's DCT scaling to decode at the
// smallest 1/1..1/8 scale that still covers (min_h, min_w), which cuts
// IDCT+color-convert work ~Nx for large sources. Returns false on
// corrupt input.
bool decode_jpeg(const uint8_t* data, size_t len, int min_h, int min_w,
                 std::vector<uint8_t>* out, int* h, int* w) {
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = on_error;
  jerr.pub.emit_message = on_emit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  // Pick largest denominator d in {8,4,2,1} with dims/d still >= target.
  cinfo.scale_num = 1;
  cinfo.scale_denom = 1;
  if (min_h > 0 && min_w > 0) {
    for (int d = 8; d >= 1; d /= 2) {
      if (static_cast<int>(cinfo.image_height) / d >= min_h &&
          static_cast<int>(cinfo.image_width) / d >= min_w) {
        cinfo.scale_denom = d;
        break;
      }
    }
  }
  jpeg_start_decompress(&cinfo);
  *h = cinfo.output_height;
  *w = cinfo.output_width;
  const int stride = cinfo.output_width * cinfo.output_components;
  out->resize(static_cast<size_t>(*h) * stride);
  // Multi-row reads: hand libjpeg a window of row pointers per call
  // (it consumes up to rec_outbuf_height — typically 1-4 — at once),
  // trimming per-call overhead vs the one-scanline loop.
  uint8_t* rows[8];
  while (cinfo.output_scanline < cinfo.output_height) {
    const JDIMENSION base = cinfo.output_scanline;
    const int want = std::min<JDIMENSION>(8, cinfo.output_height - base);
    for (int r = 0; r < want; ++r) {
      rows[r] = out->data() + (static_cast<size_t>(base) + r) * stride;
    }
    jpeg_read_scanlines(&cinfo, rows, want);
  }
  // Grayscale safety: libjpeg honors out_color_space=JCS_RGB for
  // grayscale sources too (3 components), so stride math above holds.
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// Exact bilinear resize (align_corners=false, half-pixel centers — the
// tf.image.resize v2 / PIL convention) from (sh, sw) RGB to (dh, dw).
void resize_bilinear(const uint8_t* src, int sh, int sw, uint8_t* dst,
                     int dh, int dw) {
  if (sh == dh && sw == dw) {
    std::memcpy(dst, src, static_cast<size_t>(dh) * dw * 3);
    return;
  }
  const float hs = static_cast<float>(sh) / dh;
  const float ws = static_cast<float>(sw) / dw;
  std::vector<int> x0(dw), x1(dw);
  std::vector<float> xl(dw);
  for (int x = 0; x < dw; ++x) {
    float sx = (x + 0.5f) * ws - 0.5f;
    sx = std::max(0.0f, sx);
    int xi = static_cast<int>(sx);
    x0[x] = std::min(xi, sw - 1);
    x1[x] = std::min(xi + 1, sw - 1);
    xl[x] = sx - xi;
  }
  for (int y = 0; y < dh; ++y) {
    float sy = (y + 0.5f) * hs - 0.5f;
    sy = std::max(0.0f, sy);
    int yi = static_cast<int>(sy);
    const int y0 = std::min(yi, sh - 1), y1 = std::min(yi + 1, sh - 1);
    const float yl = sy - yi;
    const uint8_t* r0 = src + static_cast<size_t>(y0) * sw * 3;
    const uint8_t* r1 = src + static_cast<size_t>(y1) * sw * 3;
    uint8_t* drow = dst + static_cast<size_t>(y) * dw * 3;
    for (int x = 0; x < dw; ++x) {
      const int a = x0[x] * 3, b = x1[x] * 3;
      const float lx = xl[x];
      for (int c = 0; c < 3; ++c) {
        const float top = r0[a + c] + (r0[b + c] - r0[a + c]) * lx;
        const float bot = r1[a + c] + (r1[b + c] - r1[a + c]) * lx;
        drow[x * 3 + c] =
            static_cast<uint8_t>(std::min(255.0f, std::max(0.0f, top + (bot - top) * yl + 0.5f)));
      }
    }
  }
}

}  // namespace

extern "C" {

// Decode+resize a batch of JPEGs into out[n, out_h, out_w, 3] (uint8,
// contiguous). ok[i] = 1 on success, 0 on corrupt input (row left
// zeroed). Returns number of failures.
int tf_decode_resize_batch(const uint8_t** jpegs, const int64_t* lens,
                           int n, int out_h, int out_w, uint8_t* out,
                           uint8_t* ok, int num_threads) {
  if (num_threads < 1) num_threads = 1;
  num_threads = std::min(num_threads, n > 0 ? n : 1);
  std::atomic<int> next(0), failures(0);
  const size_t img_sz = static_cast<size_t>(out_h) * out_w * 3;
  auto worker = [&]() {
    std::vector<uint8_t> tmp;
    int h = 0, w = 0;
    for (;;) {
      const int i = next.fetch_add(1);
      if (i >= n) break;
      uint8_t* dst = out + static_cast<size_t>(i) * img_sz;
      if (decode_jpeg(jpegs[i], static_cast<size_t>(lens[i]), out_h, out_w,
                      &tmp, &h, &w)) {
        resize_bilinear(tmp.data(), h, w, dst, out_h, out_w);
        ok[i] = 1;
      } else {
        std::memset(dst, 0, img_sz);
        ok[i] = 0;
        failures.fetch_add(1);
      }
    }
  };
  std::vector<std::thread> threads;
  threads.reserve(num_threads);
  for (int t = 0; t < num_threads; ++t) threads.emplace_back(worker);
  for (auto& t : threads) t.join();
  return failures.load();
}

// Decode a single JPEG at full resolution into caller-provided buffer
// of capacity cap bytes; writes h/w. Returns needed size, or -1 on
// corrupt input. Two-call protocol when cap is too small.
int64_t tf_decode_jpeg(const uint8_t* data, int64_t len, uint8_t* buf,
                       int64_t cap, int* h, int* w) {
  std::vector<uint8_t> tmp;
  if (!decode_jpeg(data, static_cast<size_t>(len), 0, 0, &tmp, h, w)) return -1;
  const int64_t need = static_cast<int64_t>(tmp.size());
  if (buf != nullptr && cap >= need) std::memcpy(buf, tmp.data(), need);
  return need;
}

int tf_version() { return 1; }

}  // extern "C"
