// Byte-level BPE tokenizer — the native text plane of the LM family.
//
// The reference has no text pipeline at all (its data plane is JPEG
// images); tpuflow's LM family needs corpus tokenization upstream of
// TokenDataset, and that plane belongs in native code next to the JPEG
// decoder (SURVEY.md §2b N4/N5 discipline: host-side data planes are
// C++, the TPU math is JAX).
//
// Design (the GPT-2-family recipe, simplified to pure bytes):
//  - base vocabulary = the 256 bytes; merge i creates token 256+i;
//  - PRETOKENIZATION: the byte stream splits into "pieces" starting at
//    every space/newline (the separator prefixes the next piece, so
//    " the" is one piece) — merges never cross piece boundaries;
//  - TRAINING runs on the unique-piece frequency table (classic BPE):
//    each round counts adjacent token pairs across unique pieces
//    weighted by piece count, merges the most frequent pair
//    (deterministic lowest-pair tie break), and stops early when no
//    pair repeats. Cost is rounds x unique-piece bytes — fast even for
//    large corpora, because unique pieces saturate quickly;
//  - ENCODING applies merges by rank per piece (agenda algorithm) with
//    a piece-level memo, so throughput is linear in input size;
//  - a token stream never exceeds the byte count, so callers can
//    allocate output = input length.
//
// C ABI only (ctypes binding in tpuflow/native/binding.py; pybind11 is
// not available in this image).

#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

using Piece = std::basic_string<uint8_t>;

struct PieceHash {
  size_t operator()(const Piece& p) const {
    size_t h = 1469598103934665603ull;
    for (uint8_t c : p) {
      h ^= c;
      h *= 1099511628211ull;
    }
    return h;
  }
};

// split [text, text+len) into pieces: a new piece starts AT each
// space/newline (separator attached to the following piece)
template <typename F>
void for_each_piece(const uint8_t* text, int64_t len, F&& f) {
  int64_t start = 0;
  for (int64_t i = 1; i < len; ++i) {
    if (text[i] == ' ' || text[i] == '\n') {
      f(text + start, i - start);
      start = i;
    }
  }
  if (len > start) f(text + start, len - start);
}

uint64_t pair_key(uint32_t a, uint32_t b) {
  return (uint64_t(a) << 32) | b;
}

// merge every occurrence of (a, b) -> nt in seq (in place, compacting)
void apply_merge(std::vector<uint32_t>& seq, uint32_t a, uint32_t b,
                 uint32_t nt) {
  size_t w = 0;
  for (size_t r = 0; r < seq.size(); ++r) {
    if (r + 1 < seq.size() && seq[r] == a && seq[r + 1] == b) {
      seq[w++] = nt;
      ++r;
    } else {
      seq[w++] = seq[r];
    }
  }
  seq.resize(w);
}

}  // namespace

extern "C" {

// Learn up to n_merges merges from [text, len). out_pairs holds
// n_merges * 2 uint32 slots; returns the number of merges learned
// (early stop when the best pair occurs fewer than 2 times).
int32_t tf_bpe_train(const uint8_t* text, int64_t len, int32_t n_merges,
                     uint32_t* out_pairs) {
  if (len <= 0 || n_merges <= 0) return 0;
  // unique-piece frequency table
  std::unordered_map<Piece, int64_t, PieceHash> freq;
  for_each_piece(text, len, [&](const uint8_t* p, int64_t n) {
    freq[Piece(p, p + n)] += 1;
  });
  // token sequences per unique piece
  std::vector<std::vector<uint32_t>> seqs;
  std::vector<int64_t> counts;
  seqs.reserve(freq.size());
  for (auto& kv : freq) {
    std::vector<uint32_t> s(kv.first.begin(), kv.first.end());
    seqs.push_back(std::move(s));
    counts.push_back(kv.second);
  }

  int32_t learned = 0;
  for (; learned < n_merges; ++learned) {
    std::unordered_map<uint64_t, int64_t> pc;
    for (size_t i = 0; i < seqs.size(); ++i) {
      const auto& s = seqs[i];
      for (size_t j = 0; j + 1 < s.size(); ++j)
        pc[pair_key(s[j], s[j + 1])] += counts[i];
    }
    uint64_t best = 0;
    int64_t best_n = 0;
    for (auto& kv : pc) {
      if (kv.second > best_n ||
          (kv.second == best_n && kv.first < best)) {
        best = kv.first;
        best_n = kv.second;
      }
    }
    if (best_n < 2) break;  // nothing repeats — no compression left
    uint32_t a = uint32_t(best >> 32), b = uint32_t(best & 0xffffffffu);
    out_pairs[2 * learned] = a;
    out_pairs[2 * learned + 1] = b;
    uint32_t nt = 256 + uint32_t(learned);
    for (auto& s : seqs)
      if (s.size() >= 2) apply_merge(s, a, b, nt);
  }
  return learned;
}

// Persistent encoder: holds the merge-rank map and the piece memo
// ACROSS calls, so a stream of many small documents (one encode per
// document) amortizes both — common pieces like " the" are derived
// once per encoder lifetime, not once per call.
struct TfBpeEncoder {
  std::unordered_map<uint64_t, uint32_t> rank;
  std::unordered_map<Piece, std::vector<uint32_t>, PieceHash> memo;
};

void* tf_bpe_encoder_new(const uint32_t* pairs, int32_t n_merges) {
  auto* enc = new TfBpeEncoder();
  enc->rank.reserve(size_t(n_merges) * 2);
  for (int32_t i = 0; i < n_merges; ++i)
    enc->rank[pair_key(pairs[2 * i], pairs[2 * i + 1])] = uint32_t(i);
  return enc;
}

void tf_bpe_encoder_free(void* handle) {
  delete static_cast<TfBpeEncoder*>(handle);
}

// Encode [text, len) via a persistent encoder. out must hold at least
// len uint32 (a BPE token stream never exceeds the byte count).
// Returns the number of tokens written.
int64_t tf_bpe_encoder_encode(void* handle, const uint8_t* text,
                              int64_t len, uint32_t* out) {
  if (len <= 0) return 0;
  auto* enc = static_cast<TfBpeEncoder*>(handle);
  std::vector<uint32_t> seq;
  int64_t w = 0;
  for_each_piece(text, len, [&](const uint8_t* p, int64_t n) {
    Piece key(p, p + n);
    auto it = enc->memo.find(key);
    if (it == enc->memo.end()) {
      seq.assign(key.begin(), key.end());
      // agenda: repeatedly apply the LOWEST-rank pair present
      while (seq.size() >= 2) {
        uint32_t best_rank = UINT32_MAX;
        uint32_t a = 0, b = 0;
        for (size_t j = 0; j + 1 < seq.size(); ++j) {
          auto r = enc->rank.find(pair_key(seq[j], seq[j + 1]));
          if (r != enc->rank.end() && r->second < best_rank) {
            best_rank = r->second;
            a = seq[j];
            b = seq[j + 1];
          }
        }
        if (best_rank == UINT32_MAX) break;
        apply_merge(seq, a, b, 256 + best_rank);
      }
      it = enc->memo.emplace(std::move(key), seq).first;
    }
    const auto& toks = it->second;
    std::memcpy(out + w, toks.data(), toks.size() * sizeof(uint32_t));
    w += int64_t(toks.size());
  });
  return w;
}

}  // extern "C"
