"""ctypes binding + build-on-first-use for the C++ decode plane (N4).

Compiles ``decode.cpp`` against libjpeg into a cached shared library the
first time it is needed; falls back to a Pillow implementation when no
toolchain/libjpeg is available so every code path still runs (the same
spirit as the reference's CPU fallback for its GPU pinning,
P1/03_model_training_distributed.py:276-278).
"""

from __future__ import annotations

import ctypes
import io
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "decode.cpp")
_LIB_PATH = os.path.join(_HERE, "_libtpuflow_decode.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> Optional[str]:
    """Compile to a temp file and atomically rename, under a file lock,
    so concurrent processes (one per host is the normal topology) never
    observe a half-written .so."""
    import fcntl

    lock_path = _LIB_PATH + ".lock"
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-march=native", "-fPIC", "-shared", "-std=c++17",
        _SRC, "-o", tmp, "-ljpeg", "-pthread",
    ]
    try:
        with open(lock_path, "w") as lock_f:
            fcntl.flock(lock_f, fcntl.LOCK_EX)
            if os.path.exists(_LIB_PATH) and os.path.getmtime(
                _LIB_PATH
            ) >= os.path.getmtime(_SRC):
                return _LIB_PATH  # another process built it while we waited
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, _LIB_PATH)
            return _LIB_PATH
    except Exception:
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def native_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library, or None."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        stale = os.path.exists(_LIB_PATH) and os.path.getmtime(
            _LIB_PATH
        ) < os.path.getmtime(_SRC)
        path = _LIB_PATH if os.path.exists(_LIB_PATH) and not stale else _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.tf_decode_resize_batch.restype = ctypes.c_int
        lib.tf_decode_resize_batch.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ]
        _lib = lib
        return _lib


def have_native() -> bool:
    return native_lib() is not None


def _decode_resize_batch_pil(
    jpegs: Sequence[bytes], out_h: int, out_w: int, out: np.ndarray, ok: np.ndarray
) -> int:
    from PIL import Image

    failures = 0
    for i, b in enumerate(jpegs):
        try:
            img = Image.open(io.BytesIO(b)).convert("RGB").resize(
                (out_w, out_h), Image.BILINEAR
            )
            out[i] = np.asarray(img, dtype=np.uint8)
            ok[i] = 1
        except Exception:
            out[i] = 0
            ok[i] = 0
            failures += 1
    return failures


def decode_resize_batch(
    jpegs: Sequence[bytes],
    out_h: int,
    out_w: int,
    num_threads: int = 8,
    out: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Decode a batch of JPEG byte strings to uint8 [n, out_h, out_w, 3].

    Returns (images, ok_mask). Corrupt inputs yield a zero image and
    ok=0 rather than failing the batch (a training stream must survive a
    bad file). Writes into ``out`` if given (preallocated, reused across
    steps to avoid allocator churn).
    """
    n = len(jpegs)
    if out is None:
        out = np.empty((n, out_h, out_w, 3), dtype=np.uint8)
    if out.shape != (n, out_h, out_w, 3) or out.dtype != np.uint8:
        raise ValueError(
            f"out must be uint8 {(n, out_h, out_w, 3)}, got {out.dtype} {out.shape}"
        )
    if not out.flags.c_contiguous:
        raise ValueError("out must be C-contiguous")
    ok = np.empty((n,), dtype=np.uint8)
    if n == 0:
        return out, ok
    lib = native_lib()
    if lib is None:
        _decode_resize_batch_pil(jpegs, out_h, out_w, out, ok)
        return out, ok
    bufs = [np.frombuffer(b, dtype=np.uint8) for b in jpegs]
    ptrs = (ctypes.c_void_p * n)(
        *[b.ctypes.data_as(ctypes.c_void_p).value for b in bufs]
    )
    lens = (ctypes.c_int64 * n)(*[len(b) for b in jpegs])
    lib.tf_decode_resize_batch(
        ptrs, lens, n, out_h, out_w,
        out.ctypes.data_as(ctypes.c_void_p),
        ok.ctypes.data_as(ctypes.c_void_p),
        num_threads,
    )
    return out, ok
