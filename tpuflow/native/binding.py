"""ctypes binding + build-on-first-use for the C++ decode plane (N4).

Compiles ``decode.cpp`` against libjpeg into a cached shared library the
first time it is needed; falls back to a Pillow implementation when no
toolchain/libjpeg is available so every code path still runs (the same
spirit as the reference's CPU fallback for its GPU pinning,
P1/03_model_training_distributed.py:276-278).
"""

from __future__ import annotations

import ctypes
import io
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "decode.cpp")
_LIB_PATH = os.path.join(_HERE, "_libtpuflow_decode.so")
_BPE_SRC = os.path.join(_HERE, "bpe.cpp")
_BPE_LIB_PATH = os.path.join(_HERE, "_libtpuflow_bpe.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False
_bpe_lib_handle: Optional[ctypes.CDLL] = None
_bpe_tried = False


def _build_lib(src: str, lib_path: str, link_flags: Sequence[str]) -> Optional[str]:
    """Compile to a temp file and atomically rename, under a file lock,
    so concurrent processes (one per host is the normal topology) never
    observe a half-written .so."""
    import fcntl

    lock_path = lib_path + ".lock"
    tmp = f"{lib_path}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-march=native", "-fPIC", "-shared", "-std=c++17",
        src, "-o", tmp, *link_flags,
    ]
    try:
        with open(lock_path, "w") as lock_f:
            fcntl.flock(lock_f, fcntl.LOCK_EX)
            if os.path.exists(lib_path) and os.path.getmtime(
                lib_path
            ) >= os.path.getmtime(src):
                return lib_path  # another process built it while we waited
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, lib_path)
            return lib_path
    except Exception:
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _load_lib(src: str, lib_path: str, link_flags: Sequence[str]) -> Optional[ctypes.CDLL]:
    have_so = os.path.exists(lib_path)
    if not os.path.exists(src):
        # source stripped from the deployment: load the shipped .so if
        # any (no staleness check possible), else signal fallback —
        # never raise (the 'or None' contract)
        path = lib_path if have_so else None
    else:
        stale = have_so and os.path.getmtime(lib_path) < os.path.getmtime(src)
        path = (
            lib_path if have_so and not stale
            else _build_lib(src, lib_path, link_flags)
        )
    if path is None:
        return None
    try:
        return ctypes.CDLL(path)
    except OSError:
        return None


def native_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the decode library, or None."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        lib = _load_lib(_SRC, _LIB_PATH, ("-ljpeg", "-pthread"))
        if lib is None:
            return None
        lib.tf_decode_resize_batch.restype = ctypes.c_int
        lib.tf_decode_resize_batch.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ]
        _lib = lib
        return _lib


def bpe_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the BPE tokenizer library, or None."""
    global _bpe_lib_handle, _bpe_tried
    with _lock:
        if _bpe_lib_handle is not None or _bpe_tried:
            return _bpe_lib_handle
        _bpe_tried = True
        lib = _load_lib(_BPE_SRC, _BPE_LIB_PATH, ())
        if lib is None:
            return None
        lib.tf_bpe_train.restype = ctypes.c_int32
        lib.tf_bpe_train.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p,
        ]
        lib.tf_bpe_encoder_new.restype = ctypes.c_void_p
        lib.tf_bpe_encoder_new.argtypes = [
            ctypes.c_void_p, ctypes.c_int32,
        ]
        lib.tf_bpe_encoder_free.restype = None
        lib.tf_bpe_encoder_free.argtypes = [ctypes.c_void_p]
        lib.tf_bpe_encoder_encode.restype = ctypes.c_int64
        lib.tf_bpe_encoder_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p,
        ]
        _bpe_lib_handle = lib
        return _bpe_lib_handle


def have_native() -> bool:
    return native_lib() is not None


def _decode_resize_batch_pil(
    jpegs: Sequence[bytes], out_h: int, out_w: int, out: np.ndarray, ok: np.ndarray
) -> int:
    from PIL import Image

    failures = 0
    for i, b in enumerate(jpegs):
        try:
            img = Image.open(io.BytesIO(b)).convert("RGB").resize(
                (out_w, out_h), Image.BILINEAR
            )
            out[i] = np.asarray(img, dtype=np.uint8)
            ok[i] = 1
        except Exception:
            out[i] = 0
            ok[i] = 0
            failures += 1
    return failures


def decode_resize_batch(
    jpegs: Sequence[bytes],
    out_h: int,
    out_w: int,
    num_threads: int = 8,
    out: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Decode a batch of JPEG byte strings to uint8 [n, out_h, out_w, 3].

    Returns (images, ok_mask). Corrupt inputs yield a zero image and
    ok=0 rather than failing the batch (a training stream must survive a
    bad file). Writes into ``out`` if given (preallocated, reused across
    steps to avoid allocator churn).
    """
    n = len(jpegs)
    if out is None:
        out = np.empty((n, out_h, out_w, 3), dtype=np.uint8)
    if out.shape != (n, out_h, out_w, 3) or out.dtype != np.uint8:
        raise ValueError(
            f"out must be uint8 {(n, out_h, out_w, 3)}, got {out.dtype} {out.shape}"
        )
    if not out.flags.c_contiguous:
        raise ValueError("out must be C-contiguous")
    ok = np.empty((n,), dtype=np.uint8)
    if n == 0:
        return out, ok
    lib = native_lib()
    if lib is None:
        _decode_resize_batch_pil(jpegs, out_h, out_w, out, ok)
        return out, ok
    bufs = [np.frombuffer(b, dtype=np.uint8) for b in jpegs]
    ptrs = (ctypes.c_void_p * n)(
        *[b.ctypes.data_as(ctypes.c_void_p).value for b in bufs]
    )
    lens = (ctypes.c_int64 * n)(*[len(b) for b in jpegs])
    lib.tf_decode_resize_batch(
        ptrs, lens, n, out_h, out_w,
        out.ctypes.data_as(ctypes.c_void_p),
        ok.ctypes.data_as(ctypes.c_void_p),
        num_threads,
    )
    return out, ok
