from tpuflow.native.binding import (  # noqa: F401
    bpe_lib,
    decode_resize_batch,
    have_native,
    native_lib,
)
