from tpuflow.native.binding import (  # noqa: F401
    decode_resize_batch,
    have_native,
    native_lib,
)
