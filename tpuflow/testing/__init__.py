"""Deterministic test harnesses for the fault-tolerance plane
(ISSUE 10). ``tpuflow.testing.faults`` is the fault-injection
registry; importing this package must stay side-effect-free (the
trainers import it on their hot paths)."""

from tpuflow.testing import faults  # noqa: F401
