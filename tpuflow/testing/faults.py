"""Fault-injection harness (ISSUE 10 tentpole piece 4).

Recovery code is only trustworthy if its failure paths run
DETERMINISTICALLY under test — "unplug a replica and see" does not
regress-test. This module gives the trainer/checkpoint hot paths named
injection points; tests (and ``bench.py --faults``) arm faults against
those names and the production code path itself takes the failure.

Injection points are cheap when disarmed: every hook starts with one
truthiness check of the module-level spec dict (the same discipline as
the disarmed tracer/registry, pinned by the tier-1 overhead guards of
those planes). Points currently threaded:

- ``train.step``      — per step, both trainers, K=1 and superstep
  loops (``raise`` / ``kill`` faults fire here);
- ``train.metrics``   — mutation hook over the step's device metrics
  (``nan`` faults poison the loss the health monitor sees WITHOUT
  touching device state — the rollback-replay parity tests depend on
  the replay being fault-free);
- ``ckpt.write``      — before a checkpoint payload hits disk
  (``raise`` / ``delay`` / ``kill``);
- ``ckpt.file``       — after a checkpoint file is durably in place,
  with its path (``corrupt`` / ``truncate`` flip real bytes — the
  integrity-footer fallback tests eat these);
- ``ckpt.shard``      — same, per sharded-checkpoint shard file;
- ``elastic.boundary``— superstep block boundaries (elastic resize
  tests schedule world changes here);
- ``serve.transfer.land`` — in the decode scheduler's chain inbox,
  before a KV wire chunk is applied (``delay`` makes the transfer
  phase dominate a request's SLO breakdown — the tracing/attribution
  tests and ``bench.py --serve-trace`` inject slow transfers here;
  ``raise`` exercises the transfer-abort path).

Faults are one-shot by default (``times=1``): a NaN injected at step N
trips the watchdog once, and the post-rollback REPLAY of step N runs
clean — exactly the transient-fault model auto-recovery exists for.
``times=-1`` repeats forever (the escalation-ladder tests use it).

Subprocess harnesses (kill-9 resume tests, ``bench.py --faults``
children) arm faults via ``TPUFLOW_FAULTS`` — a ``;``-separated list
of ``point=kind@step[xTIMES]`` specs parsed once at import, e.g.
``TPUFLOW_FAULTS="train.step=kill@7"``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

_LOCK = threading.Lock()
_SPECS: Dict[str, List["Fault"]] = {}
_FIRED: Dict[str, int] = {}

KINDS = ("raise", "nan", "corrupt", "truncate", "delay", "kill")


class FaultInjected(RuntimeError):
    """Raised by a ``raise``-kind fault — distinguishable from real
    failures so tests can assert the injected path specifically."""


class Fault:
    """One armed fault: ``kind`` at injection point ``point``,
    optionally gated on ``step`` (the hook's ``step=`` kwarg), firing
    at most ``times`` times (-1 = unbounded). ``delay_s`` is the sleep
    of a ``delay`` fault."""

    def __init__(self, point: str, kind: str, step: Optional[int] = None,
                 times: int = 1, delay_s: float = 0.05):
        if kind not in KINDS:
            raise ValueError(f"kind must be one of {KINDS}, got {kind!r}")
        self.point = point
        self.kind = kind
        self.step = None if step is None else int(step)
        self.times = int(times)
        self.delay_s = float(delay_s)
        self.remaining = self.times

    def matches(self, step: Optional[int]) -> bool:
        if self.remaining == 0:
            return False
        if self.step is not None and step != self.step:
            return False
        return True

    def consume(self) -> None:
        if self.remaining > 0:
            self.remaining -= 1

    def __repr__(self) -> str:  # test/debug readability
        gate = f"@{self.step}" if self.step is not None else ""
        return f"Fault({self.point}={self.kind}{gate} x{self.times})"


def inject(point: str, kind: str, step: Optional[int] = None,
           times: int = 1, delay_s: float = 0.05) -> Fault:
    """Arm a fault. Returns the handle (``remove(handle)`` disarms)."""
    f = Fault(point, kind, step=step, times=times, delay_s=delay_s)
    with _LOCK:
        _SPECS.setdefault(point, []).append(f)
    return f


def remove(fault: Fault) -> None:
    with _LOCK:
        lst = _SPECS.get(fault.point)
        if lst and fault in lst:
            lst.remove(fault)
        if lst is not None and not lst:
            _SPECS.pop(fault.point, None)


def clear(point: Optional[str] = None) -> None:
    """Disarm everything (or one point) — tests call this in teardown
    so a leaked fault can never poison the next test."""
    with _LOCK:
        if point is None:
            _SPECS.clear()
            _FIRED.clear()
        else:
            _SPECS.pop(point, None)


def fired(point: Optional[str] = None) -> "int | Dict[str, int]":
    """How many faults fired (per point, or the one point's count) —
    the assertion surface for 'the injection actually took'."""
    with _LOCK:
        if point is not None:
            return _FIRED.get(point, 0)
        return dict(_FIRED)


class injected:
    """Context-manager arming: ``with faults.injected("train.step",
    "raise", step=3): ...`` — disarmed on exit, exceptions included."""

    def __init__(self, point: str, kind: str, **kw: Any):
        self._args = (point, kind)
        self._kw = kw
        self._fault: Optional[Fault] = None

    def __enter__(self) -> Fault:
        self._fault = inject(*self._args, **self._kw)
        return self._fault

    def __exit__(self, *exc: Any) -> None:
        if self._fault is not None:
            remove(self._fault)


def _take(point: str, step: Optional[int],
          kinds: Optional[tuple] = None) -> List[Fault]:
    """Matching faults at ``point`` (consumed under the lock).
    ``kinds`` restricts which kinds a hook consumes — a hook must
    never eat (and count as fired) a fault kind it cannot act on."""
    with _LOCK:
        lst = _SPECS.get(point)
        if not lst:
            return []
        hits = [
            f for f in lst
            if f.matches(step) and (kinds is None or f.kind in kinds)
        ]
        for f in hits:
            f.consume()
            _FIRED[point] = _FIRED.get(point, 0) + 1
        return hits


def _take_range(point: str, lo: int, hi: int,
                kinds: Optional[tuple] = None) -> List[Fault]:
    """Matching faults whose step gate is None or within [lo, hi] —
    the superstep-block form of :func:`_take` (a fused K-step dispatch
    covers K global steps at once)."""
    with _LOCK:
        lst = _SPECS.get(point)
        if not lst:
            return []
        hits = [
            f for f in lst
            if f.remaining != 0 and (f.step is None or lo <= f.step <= hi)
            and (kinds is None or f.kind in kinds)
        ]
        for f in hits:
            f.consume()
            _FIRED[point] = _FIRED.get(point, 0) + 1
        return hits


def _kill() -> None:  # pragma: no cover - the process dies here
    import signal

    os.kill(os.getpid(), signal.SIGKILL)


def fire(point: str, step: Optional[int] = None) -> None:
    """Control-flow injection point: a matching ``raise`` fault raises
    :class:`FaultInjected`, ``delay`` sleeps, ``kill`` SIGKILLs the
    process (the kill-9 harness). Disarmed cost: one dict-truthiness
    check."""
    if not _SPECS:
        return
    for f in _take(point, step, kinds=("raise", "delay", "kill")):
        if f.kind == "delay":
            time.sleep(f.delay_s)
        elif f.kind == "kill":
            _kill()
        elif f.kind == "raise":
            raise FaultInjected(
                f"injected fault at {point}"
                + (f" step {step}" if step is not None else "")
            )


def mutate_metrics(point: str, metrics: Any,
                   step: Optional[int] = None, k: int = 1) -> Any:
    """Value injection point for the trainers' step-metrics dict: a
    matching ``nan`` fault poisons ``loss`` (and the ``nonfinite``
    guard flag when present) with NaN, which the health monitor's
    non-finite guard then trips on. The DEVICE state is untouched, so
    the post-rollback replay is bit-identical to an uninterrupted run
    — the acceptance criterion's fault model.

    ``step``/``k`` follow ``HealthMonitor.check_host``'s convention:
    ``step`` is the global index of the block's LAST step (== the step
    itself for scalars), ``k`` the block length — so a fault gated on
    step N poisons exactly entry ``N - (step - k + 1)`` of a fused
    superstep block, and the trip attributes to step N."""
    if not _SPECS:
        return metrics
    if step is None:
        hits = _take(point, None, kinds=("nan",))
    else:
        hits = _take_range(point, int(step) - int(k) + 1, int(step),
                           kinds=("nan",))
    if not hits:
        return metrics
    import numpy as np

    out = dict(metrics)
    lo = (int(step) - int(k) + 1) if step is not None else 0

    def _poison(val, fill):
        arr = np.array(val, np.float32)
        if arr.ndim == 0:
            return np.float32(fill)
        for f in hits:
            if f.step is None:
                arr[:] = fill
            else:
                arr[f.step - lo] = fill
        return arr

    if out.get("loss") is not None:
        out["loss"] = _poison(out["loss"], np.nan)
    if "nonfinite" in out:
        out["nonfinite"] = _poison(out["nonfinite"], 1.0)
    return out


def file_hook(point: str, path: str, step: Optional[int] = None) -> None:
    """Post-write injection point: a matching ``corrupt`` fault XORs a
    byte in the middle of ``path`` (checksum-detectable, length
    preserved); ``truncate`` chops the file's tail (the torn-write
    shape a crashed writer without atomic-replace leaves behind)."""
    if not _SPECS:
        return
    for f in _take(point, step,
                   kinds=("corrupt", "truncate", "delay", "kill")):
        if f.kind == "corrupt":
            corrupt_file(path)
        elif f.kind == "truncate":
            truncate_file(path)
        elif f.kind == "delay":
            time.sleep(f.delay_s)
        elif f.kind == "kill":  # pragma: no cover
            _kill()


def corrupt_file(path: str, offset: Optional[int] = None) -> None:
    """Flip one byte of ``path`` in place (middle of the file unless
    ``offset``) — shared by the ``corrupt`` fault and the integrity
    tests so both corrupt the same way."""
    size = os.path.getsize(path)
    if size == 0:
        return
    pos = size // 2 if offset is None else offset
    with open(path, "r+b") as fh:
        fh.seek(pos)
        b = fh.read(1)
        fh.seek(pos)
        fh.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")


def truncate_file(path: str, keep_fraction: float = 0.5) -> None:
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(max(0, int(size * keep_fraction)))


def install_from_env(env: Optional[str] = None) -> List[Fault]:
    """Parse ``TPUFLOW_FAULTS`` (or ``env``) and arm the specs —
    ``point=kind[@step][xTIMES]`` joined by ``;``. Subprocess
    harnesses (kill-9 tests, bench --faults children) use this; the
    parse happens at module import so a trainer subprocess needs no
    code change to be sabotaged."""
    spec = os.environ.get("TPUFLOW_FAULTS", "") if env is None else env
    out: List[Fault] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        point, _, rhs = part.partition("=")
        if not rhs:
            raise ValueError(f"bad fault spec {part!r} "
                             "(want point=kind[@step][xTIMES])")
        times = 1
        if "x" in rhs:
            rhs, _, t = rhs.rpartition("x")
            times = int(t)
        step: Optional[int] = None
        if "@" in rhs:
            rhs, _, s = rhs.partition("@")
            step = int(s)
        out.append(inject(point.strip(), rhs.strip(), step=step,
                          times=times))
    return out


install_from_env()
