"""Device-buffer ledger (ISSUE 7 tentpole) — the memory half of the
memory-and-compile plane.

Device memory is the resource that actually kills TPU jobs at scale,
and ``memory_stats()`` alone answers only "how full" — never "full of
WHAT". The ledger closes that gap with tags at creation sites:
long-lived device buffers are tagged by component —

- ``params`` / ``opt_state`` — the trainers' state (tagged at init and
  re-tagged at epoch boundaries, since donation replaces the arrays);
- ``kv_pages`` — the serve KV stores (paged page pools AND contiguous
  slot-pool caches) and the infer serve pools;
- ``data_staging`` — in-flight host→device batches/blocks;
- ``eval`` — evaluation batches;

and :func:`reconcile` walks ``jax.live_arrays()``: every live device
byte is attributed to its tag, and bytes NOBODY tagged show up as a
named ``untagged`` residual instead of silently vanishing — the ISSUE
6 page-scatter copy class of surprise becomes one line in one report.
Tags are weak references: a donated/deleted/garbage-collected buffer
falls out of its component on the next reconcile, never pins memory.

Beyond attribution, the ledger keeps per-component PEAK watermarks, a
bounded sample ring that :func:`tpuflow.obs.trace.export_chrome_trace`
renders as Perfetto counter tracks (a memory timeline beside the
spans), and the ``mem.hbm_headroom_bytes`` gauge the serve admission
path quotes in 429/Retry-After telemetry. Everything exports through
the shared registry (``mem.*`` in ``/v1/metrics`` + Prometheus), into
flight-recorder bundles (``memory.json``), and through
``python -m tpuflow.cli.obs memreport``.

Costs: :func:`tag` is dict writes (cheap enough for per-step staging
tags); :func:`reconcile` walks the live-array list and runs only from
sampling paths (``sample_system_metrics``) or on demand — and
:func:`maybe_update_gauges` is a no-op until something is tagged.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

_LOCK = threading.Lock()
# component -> {id(array): weakref} ; tags ACCUMULATE (re-tagging the
# same array is idempotent; dead refs are pruned at reconcile)
_TAGS: Dict[str, Dict[int, "weakref.ref"]] = {}
_PEAKS: Dict[str, int] = {}
# (wall_ts, {component: bytes, "untagged": ..., "total": ...}) samples
# for the Perfetto counter track
_SAMPLES: "deque" = deque(maxlen=4096)

#: the tag vocabulary creation sites use (free-form names work too —
#: these are the ones the repo's own sites emit)
COMPONENTS = ("params", "opt_state", "kv_pages", "eval", "data_staging")


def _is_device_array(x: Any) -> bool:
    # duck-typed: jax.Array has both; numpy has nbytes but no
    # is_deleted — keeps jax off the tag hot path entirely
    return hasattr(x, "is_deleted") and hasattr(x, "nbytes")


def tag(component: str, tree: Any) -> int:
    """Tag every device array in ``tree`` as belonging to
    ``component``. Accumulative and idempotent; an array re-tagged
    under a DIFFERENT component moves (last tag wins). Returns how
    many arrays were tagged."""
    import jax

    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if _is_device_array(x)]
    if not leaves:
        return 0
    with _LOCK:
        d = _TAGS.setdefault(component, {})
        for a in leaves:
            i = id(a)
            d[i] = weakref.ref(a)
            for oc, od in _TAGS.items():
                if oc != component:
                    od.pop(i, None)
        if len(d) > 512:
            # opportunistic prune: per-step tag sites (staging batches)
            # otherwise grow this dict one dead weakref per step until
            # a reconcile happens to run — which a plain fit with no
            # sampler armed never does
            for i in [i for i, r in d.items() if r() is None]:
                del d[i]
    return len(leaves)


def untag(component: str) -> None:
    with _LOCK:
        _TAGS.pop(component, None)


def clear() -> None:
    """Drop all tags, peaks and samples (test isolation)."""
    with _LOCK:
        _TAGS.clear()
        _PEAKS.clear()
        _SAMPLES.clear()


def enabled() -> bool:
    """Whether anything is tagged — the gate that keeps untagged
    processes from paying live-array walks in their sampling loops."""
    return bool(_TAGS)


def reconcile(live: Optional[List[Any]] = None) -> Dict[str, Any]:
    """Attribute every live device byte: walk ``jax.live_arrays()``
    (or an injected ``live`` list — unit tests), sum each component's
    still-live tagged bytes, and report the rest as ``untagged``.
    Updates peak watermarks and appends a timeline sample."""
    if live is None:
        import jax

        live = jax.live_arrays()
    live_ids: Dict[int, int] = {}
    total = 0
    for a in live:
        try:
            if a.is_deleted():
                continue
            i = id(a)
            if i in live_ids:
                continue
            nb = int(a.nbytes)
        except Exception:  # pragma: no cover - racing deletion
            continue
        live_ids[i] = nb
        total += nb
    with _LOCK:
        tags = {c: list(d.items()) for c, d in _TAGS.items()}
    components: Dict[str, int] = {}
    dead: Dict[str, List[int]] = {}
    for c, items in tags.items():
        s = 0
        for i, ref in items:
            a = ref()
            if a is None or i not in live_ids:
                dead.setdefault(c, []).append(i)
                continue
            try:
                if a.is_deleted():
                    dead.setdefault(c, []).append(i)
                    continue
            except Exception:  # pragma: no cover
                continue
            s += live_ids[i]
        components[c] = s
    with _LOCK:
        for c, ids in dead.items():
            d = _TAGS.get(c)
            if d is not None:
                for i in ids:
                    d.pop(i, None)
        tagged = sum(components.values())
        untagged = max(0, total - tagged)
        for c, v in list(components.items()) + [("untagged", untagged)]:
            if v > _PEAKS.get(c, 0):
                _PEAKS[c] = v
        peaks = dict(_PEAKS)
        sample = dict(components)
        sample["untagged"] = untagged
        sample["total"] = total
        _SAMPLES.append((time.time(), sample))
    return {
        "components": components,
        "peaks": peaks,
        "untagged_bytes": untagged,
        "tagged_bytes": tagged,
        "total_bytes": total,
        "live_arrays": len(live_ids),
        "tagged_fraction": (tagged / total) if total else 1.0,
    }


def hbm_headroom_bytes(device: Optional[Any] = None) -> Optional[float]:
    """Bytes of device memory still free — the tightest
    ``bytes_limit - bytes_in_use`` across local devices when the
    backend reports stats, else host ``MemAvailable`` (XLA:CPU buffers
    live in host RAM). None only when neither source exists."""
    import jax

    devices = [device] if device is not None else jax.local_devices()
    best: Optional[float] = None
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and "bytes_limit" in stats and "bytes_in_use" in stats:
            h = float(stats["bytes_limit"]) - float(stats["bytes_in_use"])
            best = h if best is None else min(best, h)
    if best is not None:
        return best
    from tpuflow.obs.sysmetrics import _proc_meminfo

    avail = _proc_meminfo().get("MemAvailable")
    return float(avail) if avail is not None else None


def update_gauges(live: Optional[List[Any]] = None) -> Dict[str, Any]:
    """Reconcile and publish the ledger as ``mem.*`` gauges — the
    export every consumer (``/v1/metrics``, Prometheus, the snapshot
    ring, flight gauges) reads. Returns the reconcile report."""
    from tpuflow.obs.gauges import set_gauge

    rep = reconcile(live)
    for c, v in rep["components"].items():
        set_gauge(f"mem.{c}_bytes", float(v))
    for c, v in rep["peaks"].items():
        set_gauge(f"mem.{c}_peak_bytes", float(v))
    set_gauge("mem.untagged_bytes", float(rep["untagged_bytes"]))
    set_gauge("mem.live_bytes", float(rep["total_bytes"]))
    set_gauge("mem.live_arrays", float(rep["live_arrays"]))
    hb = hbm_headroom_bytes()
    if hb is not None:
        set_gauge("mem.hbm_headroom_bytes", float(hb))
    return rep


def maybe_update_gauges() -> Optional[Dict[str, Any]]:
    """``update_gauges`` gated on :func:`enabled` — what the periodic
    samplers call, so untagged processes pay one dict-truthiness
    check and nothing else."""
    if not _TAGS:
        return None
    return update_gauges()


def counter_events(pid: int) -> List[Dict[str, Any]]:
    """The ledger timeline as Chrome trace counter events (``ph: "C"``)
    — one stacked per-component track plus the total, rendered by
    Perfetto beside the span tracks
    (:func:`tpuflow.obs.trace.export_chrome_trace` merges these in)."""
    with _LOCK:
        samples = list(_SAMPLES)
    events = []
    for ts, vals in samples:
        args = {k: float(v) for k, v in vals.items() if k != "total"}
        events.append({
            "ph": "C", "name": "mem.component_bytes", "cat": "tpuflow",
            "pid": pid, "tid": 0, "ts": round(ts * 1e6, 3), "args": args,
        })
    return events


def snapshot() -> Optional[Dict[str, Any]]:
    """Ledger state for the flight recorder's ``memory.json`` section
    (None when nothing was ever tagged — quiet processes add no
    noise). Includes a fresh reconcile so the bundle carries the
    at-death attribution, plus the recent timeline."""
    if not _TAGS and not _SAMPLES:
        return None
    rep = reconcile()
    with _LOCK:
        timeline = [
            {"ts": ts, **{k: int(v) for k, v in vals.items()}}
            for ts, vals in list(_SAMPLES)[-64:]
        ]
    rep["hbm_headroom_bytes"] = hbm_headroom_bytes()
    rep["timeline"] = timeline
    return rep


# ---- report rendering (the memreport CLI + tools shim) --------------

def _mb(v) -> str:
    return f"{v / 1e6:.2f} MB" if v is not None else "?"


def format_memory_section(rep: Dict[str, Any]) -> str:
    """Human rendering of one ledger report/snapshot."""
    lines = ["device-buffer ledger:"]
    comps = rep.get("components", {})
    total = rep.get("total_bytes", 0)
    peaks = rep.get("peaks", {})
    rows = sorted(comps.items(), key=lambda kv: -kv[1])
    rows.append(("untagged", rep.get("untagged_bytes", 0)))
    for name, v in rows:
        frac = (v / total * 100.0) if total else 0.0
        pk = peaks.get(name)
        lines.append(
            f"  {name:<14} {_mb(v):>12}  ({frac:5.1f}%)"
            + (f"  peak {_mb(pk)}" if pk is not None else "")
        )
    lines.append(
        f"  {'total':<14} {_mb(total):>12}  "
        f"({rep.get('live_arrays', 0)} live arrays, "
        f"{rep.get('tagged_fraction', 0) * 100:.1f}% tagged)"
    )
    hb = rep.get("hbm_headroom_bytes")
    if hb is not None:
        lines.append(f"  headroom       {_mb(hb):>12}")
    return "\n".join(lines)


def format_executables_section(snap: Dict[str, Any]) -> str:
    """Human rendering of the executable-registry snapshot: one row
    per site (compiles/calls/wall), cost+roofline when captured, and
    the compile-cache hit/miss table."""
    lines = [
        f"executable registry ({'armed' if snap.get('enabled') else 'disarmed'}, "
        f"{snap.get('compiles_total', 0)} compiles, recompile threshold "
        f"{snap.get('recompile_threshold')}):"
    ]
    sites = snap.get("sites", {})
    for key in sorted(sites):
        s = sites[key]
        lines.append(
            f"  {key:<24} {s.get('kind', 'jit'):<4} "
            f"compiles={s.get('compiles', 0)} calls={s.get('calls', 0)} "
            f"wall={s.get('wall_s_total', 0.0):.2f}s"
            + ("  TRIPPED" if s.get("tripped") else "")
        )
        cost = s.get("cost")
        if cost:
            ai = cost.get("arithmetic_intensity")
            lines.append(
                f"    flops={cost.get('flops', 0):.3g} "
                f"bytes={cost.get('bytes_accessed', 0):.3g}"
                + (f" AI={ai:.2f} ({cost.get('verdict', '?')})"
                   if ai is not None else "")
            )
        mem = s.get("memory")
        if mem:
            lines.append(
                f"    temp={_mb(mem.get('temp_bytes'))} "
                f"args={_mb(mem.get('argument_bytes'))} "
                f"out={_mb(mem.get('output_bytes'))} "
                f"alias={_mb(mem.get('alias_bytes'))}"
            )
        if s.get("shapes"):
            lines.append(f"    shapes: {s['shapes'][-1]}")
    caches = snap.get("caches", {})
    for name in sorted(caches):
        c = caches[name]
        lines.append(
            f"  cache {name:<18} size={c.get('size', 0)}/"
            f"{c.get('maxsize', 0)} hits={c.get('hits', 0)} "
            f"misses={c.get('misses', 0)} evictions={c.get('evictions', 0)}"
        )
    return "\n".join(lines)


def format_kv_section(snap: Dict[str, Any]) -> str:
    """The KV sub-view (absorbed from ``tools/kv_memory_report.py``):
    page occupancy, allocator counters, prefix-tree stats,
    bytes-per-live-token, per-pool live rows."""
    lines = []
    total, used = snap.get("pages_total", 0), snap.get("pages_in_use", 0)
    pb = snap.get("page_bytes", 0)
    lines.append(
        f"pages: {used}/{total} in use "
        f"({snap.get('kv_bytes_in_use', 0) / 1e6:.2f} / "
        f"{snap.get('kv_bytes_total', 0) / 1e6:.2f} MB, "
        f"{pb} B/page, page_size={snap.get('page_size')}, "
        f"quant={snap.get('quant')})"
    )
    lines.append(
        f"allocator: {snap.get('allocs', 0)} allocs, "
        f"{snap.get('frees', 0)} frees, "
        f"{snap.get('alloc_failures', 0)} failures, "
        f"free-rate {snap.get('free_rate_per_s', 0)}/s"
    )
    if snap.get("page_extends") or snap.get("held_vs_budget_mean"):
        hb = snap.get("held_vs_budget_mean")
        lines.append(
            f"incremental allocation: {snap.get('page_extends', 0)} "
            f"extends, mean held/budget "
            f"{'n/a' if hb is None else hb} (released requests)"
        )
    live = snap.get("live_kv_tokens", 0)
    bplt = snap.get("bytes_per_live_token")
    lines.append(
        f"live KV tokens: {live}"
        + (f" -> {bplt} bytes/live-token" if bplt else "")
    )
    pfx = snap.get("prefix")
    if pfx:
        lines.append(
            f"prefix tree: {pfx.get('nodes', 0)} nodes "
            f"(depth {pfx.get('max_depth', 0)}), "
            f"{pfx.get('inserts', 0)} inserts, "
            f"{pfx.get('evictions', 0)} evictions"
        )
    tier = snap.get("tier")
    if tier:
        lines.append(
            f"tier host: {tier.get('host_bytes_used', 0) / 1e6:.2f}/"
            f"{tier.get('host_bytes_budget', 0) / 1e6:.2f} MB, "
            f"{tier.get('host_chains', 0)} chains"
        )
        if tier.get("disk_chains") or tier.get("disk_spills"):
            lines.append(
                f"tier disk: {tier.get('disk_bytes_used', 0) / 1e6:.2f} "
                f"MB, {tier.get('disk_chains', 0)} chains "
                f"({tier.get('disk_spills', 0)} spills, "
                f"{tier.get('disk_loads', 0)} loads)"
            )
        lines.append(
            f"tier flow: {tier.get('demotes', 0)} demotes "
            f"({tier.get('demoted_pages', 0)} pages), "
            f"{tier.get('promotes', 0)} promotes "
            f"({tier.get('promoted_pages', 0)} pages), "
            f"{tier.get('drops', 0)} drops, "
            f"{tier.get('corrupt_drops', 0)} corrupt"
        )
    pools = snap.get("pools") or {}
    for b in sorted(pools, key=lambda x: int(x)):
        rows = pools[b]
        lines.append(f"pool bucket={b}: {len(rows)} live rows")
        for r in rows:
            hb = r.get("held_vs_budget")
            lines.append(
                f"  slot {r['slot']}: {r['id']} kv_len={r['kv_len']} "
                f"pages={r['pages']}"
                + (f"/{r['budget_pages']} budget ({hb}x)"
                   if r.get("budget_pages") else "")
                + f" shared_prefix={r['shared_prefix_tokens']} tok"
            )
    return "\n".join(lines)


def format_memreport(bundle: Dict[str, Any]) -> str:
    """One memory-and-compile report from a loaded flight bundle
    (:func:`tpuflow.obs.flight.load`): ledger + executables + every
    ``*_kv`` KV section — the ``cli.obs memreport`` payload."""
    lines = [f"memreport: {bundle.get('_path', '<live>')}"]
    if bundle.get("memory"):
        lines.append(format_memory_section(bundle["memory"]))
    else:
        lines.append("(no memory section — nothing was tagged)")
    if bundle.get("executables"):
        lines.append(format_executables_section(bundle["executables"]))
    for key in sorted(bundle):
        if key.endswith("_kv") and bundle[key]:
            lines.append(f"KV [{key}]:")
            lines.append(format_kv_section(bundle[key]))
    return "\n".join(lines)


def live_report() -> str:
    """The CURRENT process's memory-and-compile report (examples,
    notebooks, tests) — same rendering as the bundle path."""
    from tpuflow.obs import executables

    bundle: Dict[str, Any] = {"_path": "<live process>"}
    if enabled():
        rep = reconcile()
        rep["hbm_headroom_bytes"] = hbm_headroom_bytes()
        bundle["memory"] = rep
    bundle["executables"] = executables.snapshot()
    return format_memreport(bundle)
