"""Profiling (SURVEY.md §5.1) — the Horovod-Timeline equivalent.

The reference's only op-level tracer is the opt-in Horovod Timeline
written to JSON for chrome://tracing (P1/03_model_training_distributed.py:407-409),
plus MLflow autolog for per-epoch metrics. Here:

- ``trace(logdir)`` wraps ``jax.profiler`` and produces a
  TensorBoard/Perfetto trace of N steps (device + host timelines, XLA
  op breakdown — strictly more than Horovod Timeline showed);
- ``annotate(name)`` marks host-code regions so loader/step phases are
  attributable in the trace;
- opt-in via env var TPUFLOW_TRACE_DIR as the reference's
  HOROVOD_TIMELINE was env-driven, or programmatic.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional


@contextlib.contextmanager
def trace(logdir: Optional[str] = None) -> Iterator[Optional[str]]:
    """Capture a profiler trace around the enclosed steps.

    No-op when logdir is None and TPUFLOW_TRACE_DIR is unset, so the
    call can stay in production code (the timeline's "only enable when
    debugging" warning, P1/03:408, becomes a default)."""
    import jax

    logdir = logdir or os.environ.get("TPUFLOW_TRACE_DIR")
    if not logdir:
        yield None
        return
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named host-region annotation visible in traces."""
    import jax

    return jax.profiler.TraceAnnotation(name)
