"""Process-wide structured span tracer — ONE answer to "where did this
step/request spend its time" (ISSUE 4 tentpole).

The paper's monitoring story is three disconnected surfaces (Ganglia
dashboards, opt-in Horovod Timeline JSON, MLflow per-epoch metrics —
P1/03:407-409, P1/04:25-30) and the reproduction mirrored that split:
sysmetrics pulls, gauges push, serve kept private percentile math, and
trainer timing lived in bench diagnostics. This module is the common
spine, in the spirit of Dapper (Sigelman et al., 2010): every hot path
(train epoch/superstep/staging, infer prefill/decode/compile, serve
request lifecycle) emits SPANS into one ring buffer, correlated by
trace ids — the serving runtime reuses request ids as trace ids, so
``/v1/metrics`` events and ``/v1/trace/<id>`` spans describe the same
request.

Design contract:

- **near-zero overhead when disabled** (the default): :func:`span`
  checks one module flag and returns a shared no-op context manager —
  no allocation, no lock, no clock read — so instrumentation stays in
  production code permanently, like :func:`tpuflow.obs.profiler.trace`
  does for the jax profiler. A tier-1 guard test pins the disabled
  overhead (<2% on a tight instrumented loop).
- **thread-safe, bounded**: finished spans land in a ring buffer
  (``capacity`` newest kept) under a lock; a long-lived server cannot
  grow without limit.
- **timestamps** are ``time.perf_counter_ns`` (monotonic, ns); a wall
  anchor captured at :func:`enable` maps them to epoch microseconds on
  export so host spans line up with ``jax.profiler`` captures.
- **ids** propagate via ``contextvars``: ``with span(...)`` nests
  parent/child ids within a thread AND across ``contextvars`` copies;
  :func:`begin`/:func:`end` carry a span across threads explicitly
  (the serving scheduler starts a request's queue span on the HTTP
  thread and ends it on the scheduler thread).
- **export**: :func:`export_chrome_trace` writes Chrome trace-event
  JSON (``ph: "X"`` complete events on per-thread tracks) loadable in
  Perfetto / ``chrome://tracing`` alongside ``jax.profiler`` output;
  :mod:`tpuflow.obs.report` turns the same spans into a step-time
  breakdown (host-dispatch vs device vs data-wait).

Enable programmatically (``trace.enable()``) or via the environment
(``TPUFLOW_TRACE_SPANS=1`` — the same opt-in idiom as the reference's
``HOROVOD_TIMELINE`` env hook).
"""

from __future__ import annotations

import collections
import contextvars
import itertools
import json
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

_LOCK = threading.Lock()
_ENABLED = False  # fast-path flag: read unlocked on every span() call
_RING: "collections.deque[Dict[str, Any]]" = collections.deque(maxlen=65536)
# wall anchor: (time.time(), perf_counter_ns) at enable() — maps the
# monotonic span clock onto epoch time for export/correlation. Export
# paths prefer each record's OWN ``t0_wall`` (stamped at commit time);
# the module anchor is only the fallback for records committed by an
# older tracer build.
_ANCHOR: Tuple[float, int] = (time.time(), time.perf_counter_ns())
# span/trace ids are seeded by pid so ids minted by DIFFERENT worker
# processes never collide when the router merges a tier trace — within
# one process the counter stays strictly increasing as before
_IDS = itertools.count(((os.getpid() & 0x7FFF) << 40) + 1)
# (trace_id, span_id) of the innermost open `with span(...)` in this
# context; inherited by threads only through explicit begin(trace_id=)
# or contextvars.copy_context (plain threading.Thread starts fresh)
_CTX: "contextvars.ContextVar[Optional[Tuple[Any, int]]]" = (
    contextvars.ContextVar("tpuflow_trace_ctx", default=None)
)

# ---- bounded always-on sampling (ISSUE 19) --------------------------
# head-sample 1-in-N (deterministic on the trace id, so the router and
# every worker that adopts its trace context make the SAME decision
# with no extra wire field) + tail-keep: a head-dropped request's spans
# are buffered per trace and COMMITTED anyway when the request errors
# or lands past the tail latency threshold / the windowed p95 — the
# outliers are exactly the traces worth keeping at fleet rates.
_SAMPLE_HEAD_N = 1  # 1 = trace everything (the pre-ISSUE-19 behavior)
_SAMPLE_TAIL_SLOW_MS: Optional[float] = None
_PENDING: "collections.OrderedDict[Any, List[Dict[str, Any]]]" = (
    collections.OrderedDict()
)
_PENDING_MAX_TRACES = 256
_PENDING_MAX_SPANS = 512
# recent request latencies (kept AND dropped) — the tail-keep p95 base
_LAT_WINDOW: "collections.deque[float]" = collections.deque(maxlen=512)
_LAT_MIN_SAMPLES = 16


class Span:
    """One open span (hand it to :func:`end` to finish it)."""

    __slots__ = ("name", "trace", "span", "parent", "t0", "tid",
                 "thread", "attrs", "_done")

    def __init__(self, name: str, trace: Any, span_id: int,
                 parent: Optional[int], attrs: Dict[str, Any]):
        self.name = name
        self.trace = trace
        self.span = span_id
        self.parent = parent
        self.attrs = attrs
        t = threading.current_thread()
        self.tid = t.ident or 0
        self.thread = t.name
        self._done = False
        self.t0 = time.perf_counter_ns()  # last: exclude setup from dur


class _Noop:
    """Shared disabled-path context manager: no state, reentrant."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


class _SpanCM:
    """Enabled-path context manager: begin + context push on enter,
    context pop + end on exit."""

    __slots__ = ("_name", "_attrs", "_span", "_tok")

    def __init__(self, name: str, attrs: Dict[str, Any]):
        self._name = name
        self._attrs = attrs
        self._span = None
        self._tok = None

    def __enter__(self) -> Optional[Span]:
        s = begin(self._name, **self._attrs)
        self._span = s
        if s is not None:
            self._tok = _CTX.set((s.trace, s.span))
        return s

    def __exit__(self, *exc):
        if self._tok is not None:
            _CTX.reset(self._tok)
            self._tok = None
        end(self._span)
        return False


# ---- lifecycle ------------------------------------------------------

def enable(capacity: int = 65536, clear: bool = True) -> None:
    """Turn the tracer on (idempotent). ``capacity`` bounds the ring of
    FINISHED spans (oldest dropped); ``clear`` empties any previous
    capture."""
    global _ENABLED, _RING, _ANCHOR
    if capacity < 1:
        raise ValueError(f"capacity must be >= 1, got {capacity}")
    with _LOCK:
        if clear or _RING.maxlen != capacity:
            _RING = collections.deque(
                [] if clear else _RING, maxlen=capacity
            )
        _ANCHOR = (time.time(), time.perf_counter_ns())
        _ENABLED = True


def disable() -> None:
    """Turn the tracer off. Already-open spans ended afterwards are
    dropped; the captured ring stays readable/exportable."""
    global _ENABLED
    with _LOCK:
        _ENABLED = False


def is_enabled() -> bool:
    return _ENABLED


def clear() -> None:
    with _LOCK:
        _RING.clear()
        _PENDING.clear()
        _LAT_WINDOW.clear()


# ---- span creation --------------------------------------------------

def span(name: str, **attrs: Any):
    """Context manager for a same-thread span. The production-code
    idiom: ``with span("train.dispatch", phase="dispatch"): ...`` —
    when the tracer is disabled this returns a shared no-op object
    (one flag read, nothing else)."""
    if not _ENABLED:
        return _NOOP
    return _SpanCM(name, attrs)


def begin(name: str, trace_id: Any = None, parent_id: Optional[int] = None,
          **attrs: Any) -> Optional[Span]:
    """Open a span explicitly (cross-thread spans: begin on one thread,
    :func:`end` on another). Returns ``None`` when disabled — and
    ``end(None)`` is a no-op, so callers never need their own guard.

    ``trace_id``: correlation id; defaults to the context's current
    trace (or a fresh id at top level). The serving runtime passes the
    REQUEST id here. ``parent_id``: explicit parent span id; defaults
    to the context's innermost open span."""
    if not _ENABLED:
        return None
    ctx = _CTX.get()
    if trace_id is None:
        trace_id = ctx[0] if ctx is not None else next(_IDS)
    if parent_id is None and ctx is not None:
        parent_id = ctx[1]
    return Span(name, trace_id, next(_IDS), parent_id, attrs)


def end(s: Optional[Span], **attrs: Any) -> None:
    """Finish a span and commit it to the ring. Idempotent; ``None`` is
    accepted (the disabled-at-begin case). Extra ``attrs`` merge in —
    e.g. the terminal state of a request."""
    if s is None or s._done:
        return
    t1 = time.perf_counter_ns()
    s._done = True
    if not _ENABLED:
        return  # disabled mid-span: drop rather than record a torn ring
    if attrs:
        s.attrs.update(attrs)
    # per-span wall anchor, stamped at COMMIT time (ISSUE 19 satellite):
    # a re-enable() mid-flight replaces the module anchor, so export
    # must never map an old record through the new epoch — each record
    # carries its own epoch start instead
    wall0, pc0 = _ANCHOR
    rec = {
        "name": s.name,
        "trace": s.trace,
        "span": s.span,
        "parent": s.parent,
        "t0_ns": s.t0,
        "t1_ns": t1,
        "t0_wall": wall0 + (s.t0 - pc0) / 1e9,
        "dur_ms": (t1 - s.t0) / 1e6,
        "tid": s.tid,
        "thread": s.thread,
        "attrs": s.attrs,
    }
    with _LOCK:
        pend = _PENDING.get(s.trace)
        if pend is not None:  # head-dropped trace: buffer for tail-keep
            if len(pend) < _PENDING_MAX_SPANS:
                pend.append(rec)
            return
        _RING.append(rec)


def current_trace_id() -> Any:
    """Trace id of the innermost open ``with span(...)`` in this
    context (None at top level)."""
    ctx = _CTX.get()
    return ctx[0] if ctx is not None else None


# ---- inspection -----------------------------------------------------

def snapshot(name: Optional[str] = None,
             trace_id: Any = None) -> List[Dict[str, Any]]:
    """Finished spans, oldest first, optionally filtered by span name
    and/or trace id. Returns copies — callers can't corrupt the ring."""
    with _LOCK:
        spans = list(_RING)
    if name is not None:
        spans = [s for s in spans if s["name"] == name]
    if trace_id is not None:
        spans = [s for s in spans if s["trace"] == trace_id]
    return [dict(s) for s in spans]


def spans_for(trace_id: Any) -> List[Dict[str, Any]]:
    """JSON-safe spans of one trace (the ``/v1/trace/<request_id>``
    payload): durations in ms, start offsets relative to the wall
    anchor, attrs coerced to JSON scalars."""
    out = []
    for s in snapshot(trace_id=trace_id):
        out.append({
            "name": s["name"],
            "span_id": s["span"],
            "parent_id": s["parent"],
            "thread": s["thread"],
            "start_s": round(_rec_wall(s), 6),
            "dur_ms": round(s["dur_ms"], 3),
            "attrs": {k: _jsonable(v) for k, v in s["attrs"].items()},
        })
    return out


def _rec_wall(rec: Dict[str, Any]) -> float:
    """Epoch start of one ring record — the record's own commit-time
    anchor when present (always, since ISSUE 19), else the module
    anchor (records from an older build)."""
    w = rec.get("t0_wall")
    if w is not None:
        return float(w)
    wall0, pc0 = _ANCHOR
    return wall0 + (rec["t0_ns"] - pc0) / 1e9


def phase_totals_ms(prefix: Optional[str] = None) -> Dict[str, float]:
    """Total duration per span NAME over the captured ring (optionally
    filtered to names under ``prefix``) — the per-phase accounting
    bench.py attaches to every capture's diagnostics."""
    totals: Dict[str, float] = {}
    with _LOCK:
        spans = list(_RING)
    for s in spans:
        n = s["name"]
        if prefix is not None and not n.startswith(prefix):
            continue
        totals[n] = totals.get(n, 0.0) + s["dur_ms"]
    return {k: round(v, 3) for k, v in totals.items()}


# ---- export ---------------------------------------------------------

def _jsonable(v: Any) -> Any:
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    try:  # numpy scalars and friends
        return v.item()
    except Exception:
        return str(v)


def export_chrome_trace(path: str) -> str:
    """Write the captured spans as Chrome trace-event JSON (``ph: "X"``
    complete events, epoch-anchored µs timestamps, one track per host
    thread) — loadable in Perfetto / ``chrome://tracing``, including
    side-by-side with a ``jax.profiler`` capture of the same run.
    Returns ``path``."""
    pid = os.getpid()
    with _LOCK:
        spans = list(_RING)
    events: List[Dict[str, Any]] = [{
        "ph": "M", "name": "process_name", "pid": pid,
        "args": {"name": "tpuflow host spans"},
    }]
    threads: Dict[int, str] = {}
    for s in spans:
        threads.setdefault(s["tid"], s["thread"])
    for tid, tname in sorted(threads.items()):
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": tname}})
    for s in spans:
        ts_us = _rec_wall(s) * 1e6
        args = {k: _jsonable(v) for k, v in s["attrs"].items()}
        args["trace_id"] = _jsonable(s["trace"])
        args["span_id"] = s["span"]
        if s["parent"] is not None:
            args["parent_id"] = s["parent"]
        events.append({
            "ph": "X", "name": s["name"], "cat": "tpuflow",
            "pid": pid, "tid": s["tid"],
            "ts": round(ts_us, 3),
            "dur": round((s["t1_ns"] - s["t0_ns"]) / 1e3, 3),
            "args": args,
        })
    # memory-ledger counter tracks (ISSUE 7): the per-component device
    # byte timeline renders as stacked Perfetto counters beside the
    # span tracks — lazy + guarded so the exporter never depends on
    # the ledger being armed
    try:
        from tpuflow.obs import memory as _memory

        events.extend(_memory.counter_events(pid))
    except Exception:  # pragma: no cover - ledger must not break export
        pass
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    os.replace(tmp, path)  # atomic: never a torn artifact
    return path


# ---- sampling (ISSUE 19) --------------------------------------------

def configure_sampling(head_n: int = 1,
                       tail_slow_ms: Optional[float] = None) -> None:
    """Bounded always-on sampling: ``head_n`` keeps 1-in-N requests up
    front (deterministic on the trace id, so every process in the tier
    agrees); ``tail_slow_ms`` keeps a head-dropped request anyway when
    it errors or finishes at/above the threshold — and, once the
    latency window is warm, at/above the windowed p95 (outlier
    tail-keep). ``head_n=1`` with ``tail_slow_ms=None`` is the
    trace-everything default."""
    global _SAMPLE_HEAD_N, _SAMPLE_TAIL_SLOW_MS
    if int(head_n) < 1:
        raise ValueError(f"head_n must be >= 1, got {head_n}")
    with _LOCK:
        _SAMPLE_HEAD_N = int(head_n)
        _SAMPLE_TAIL_SLOW_MS = (
            None if tail_slow_ms is None else float(tail_slow_ms))


def sampling() -> Dict[str, Any]:
    return {"head_n": _SAMPLE_HEAD_N,
            "tail_slow_ms": _SAMPLE_TAIL_SLOW_MS}


def head_sampled(trace_id: Any) -> bool:
    """The head decision for one trace id — stable across processes
    (crc32 of the id string), so a worker adopting the router's trace
    context independently reaches the same verdict."""
    n = _SAMPLE_HEAD_N
    if n <= 1:
        return True
    return zlib.crc32(str(trace_id).encode()) % n == 0


def begin_request(trace_id: Any) -> bool:
    """Register one request with the sampler. Returns the head
    decision; a head-dropped request's spans are buffered (bounded)
    so :func:`finish_request` can still tail-keep them. No-op (False)
    when the tracer is disabled."""
    if not _ENABLED:
        return False
    if head_sampled(trace_id):
        return True
    with _LOCK:
        if trace_id not in _PENDING:
            _PENDING[trace_id] = []
            while len(_PENDING) > _PENDING_MAX_TRACES:
                _PENDING.popitem(last=False)
    return False


def finish_request(trace_id: Any, *, error: bool = False,
                   latency_ms: Optional[float] = None) -> bool:
    """Settle one request's sampling fate. Head-sampled requests are
    already in the ring (returns True). Head-dropped requests are
    COMMITTED anyway — tail-keep — when they errored, crossed the
    configured ``tail_slow_ms``, or landed at/above the windowed p95;
    otherwise their buffered spans drop. Every latency feeds the
    outlier window either way."""
    with _LOCK:
        prior = list(_LAT_WINDOW) if _PENDING else []
        if latency_ms is not None:
            _LAT_WINDOW.append(float(latency_ms))
        pend = _PENDING.pop(trace_id, None)
        if pend is None:
            return _ENABLED
        keep = bool(error)
        if not keep and latency_ms is not None:
            thr = _SAMPLE_TAIL_SLOW_MS
            if thr is not None and latency_ms >= thr:
                keep = True
            elif len(prior) >= _LAT_MIN_SAMPLES:
                prior.sort()
                p95 = prior[min(len(prior) - 1,
                                int(0.95 * (len(prior) - 1) + 0.5))]
                keep = latency_ms >= p95
        if keep:
            _RING.extend(pend)
        return keep


# ---- cross-process clock alignment + tier merge (ISSUE 19) ----------

def wall_anchor() -> Dict[str, float]:
    """This process's wall anchor — shipped in ``load_snapshot()`` /
    ``health()`` so the router can estimate the per-replica clock
    offset from the probe's RTT midpoint."""
    return {"wall_s": time.time()}


def merge_tier_spans(
    parts: List[Tuple[str, float, List[Dict[str, Any]]]],
) -> List[Dict[str, Any]]:
    """Merge per-process :func:`spans_for` payloads into ONE tier
    timeline: ``parts`` is ``[(source, offset_s, spans)]`` where
    ``offset_s`` is the source clock minus the merger's clock (the
    router's RTT-midpoint estimate). Each span's ``start_s`` is
    offset-corrected into the merger's epoch, then clamped so a child
    never starts before its parent — residual skew below the estimate's
    error bound cannot produce a non-monotone parent/child edge."""
    merged: List[Dict[str, Any]] = []
    for source, offset_s, spans in parts:
        for s in spans or []:
            c = dict(s)
            c["source"] = source
            c["start_s"] = round(float(s["start_s"]) - float(offset_s), 6)
            merged.append(c)
    # event instants carry span_id None — keep them out of the id map
    # so a root span (parent_id None) never "finds" an instant as its
    # parent and gets clamped against it
    by_id = {s["span_id"]: s for s in merged
             if s.get("span_id") is not None}
    # clamp parent-first (memoized walk up the parent chain): start
    # order is NOT topological here — an over-corrected part can put a
    # whole subtree before its cross-source parent, and a child must
    # clamp against its parent's CLAMPED start, not the raw one
    resolved = set()

    def _clamp(s):
        sid = s.get("span_id")
        if sid in resolved:
            return s["start_s"]
        if sid is not None:
            # marked before the parent walk: a malformed parent cycle
            # short-circuits instead of recursing forever
            resolved.add(sid)
        pid = s.get("parent_id")
        p = by_id.get(pid) if pid is not None else None
        if p is not None and p is not s:
            ps = _clamp(p)
            if s["start_s"] < ps:
                s["start_s"] = ps
        return s["start_s"]

    for s in merged:
        _clamp(s)
    merged.sort(key=lambda s: (s["start_s"], s["span_id"] or 0))
    return merged


def export_chrome_spans(path: str, spans: List[Dict[str, Any]],
                        label: str = "tpuflow tier trace") -> str:
    """Write merged :func:`spans_for`-shaped spans (``start_s`` epoch
    seconds, ``dur_ms``) as Chrome trace-event JSON — one pid track per
    ``source`` so a tier trace renders router and replicas side by
    side. Returns ``path``."""
    sources: List[str] = []
    for s in spans:
        src = str(s.get("source", "local"))
        if src not in sources:
            sources.append(src)
    events: List[Dict[str, Any]] = []
    for pid, src in enumerate(sources, start=1):
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "args": {"name": f"{label}: {src}"}})
    for s in spans:
        pid = sources.index(str(s.get("source", "local"))) + 1
        args = {k: _jsonable(v)
                for k, v in (s.get("attrs") or {}).items()}
        args["span_id"] = s["span_id"]
        if s.get("parent_id") is not None:
            args["parent_id"] = s["parent_id"]
        ev: Dict[str, Any] = {
            "ph": "X", "name": s["name"], "cat": "tpuflow",
            "pid": pid, "tid": 1,
            "ts": round(float(s["start_s"]) * 1e6, 3),
            "dur": round(float(s["dur_ms"]) * 1e3, 3),
            "args": args,
        }
        if s.get("instant"):
            ev["ph"] = "i"
            ev["s"] = "t"
            ev.pop("dur")
        events.append(ev)
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    os.replace(tmp, path)
    return path


# env opt-in, the HOROVOD_TIMELINE idiom: a server/job launched with
# TPUFLOW_TRACE_SPANS=1 traces from its first import with no code change
if os.environ.get("TPUFLOW_TRACE_SPANS"):  # pragma: no cover - env path
    enable()
