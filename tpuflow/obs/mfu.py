"""MFU accounting (SURVEY.md §7 hard part 5, N11).

The north-star metric is images/sec/chip at ≥60% MFU (BASELINE.md).
FLOPs per step come from XLA's own cost analysis of the compiled
executable — honest numbers that track the real program, not a paper
formula; peak chip FLOP/s comes from a per-generation table
(bf16, dense) overridable via TPUFLOW_PEAK_FLOPS.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

# bf16 dense peak FLOP/s per chip by TPU generation (public specs).
_PEAK_BF16 = {
    "v2": 45e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "v6 lite": 918e12,
}

# HBM bandwidth (bytes/s) per chip by generation (public specs) — the
# denominator for bandwidth-bound metrics (autoregressive decode reads
# every parameter once per token).
_HBM_BW = {
    "v2": 700e9,
    "v3": 900e9,
    "v4": 1228e9,
    "v5e": 819e9,
    "v5 lite": 819e9,
    "v5p": 2765e9,
    "v6e": 1640e9,
    "v6 lite": 1640e9,
}


def _device_spec(device, table, env_var: str, cpu_nominal: float,
                 default: float) -> float:
    """One lookup template for per-generation chip specs: env override,
    device_kind substring match against ``table``, CPU nominal for
    testability, v4 default otherwise — shared so the peak-FLOPs and
    HBM-bandwidth lookups can never drift procedurally."""
    env = os.environ.get(env_var)
    if env:
        return float(env)
    import jax

    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, val in table.items():
        if key in kind:
            return val
    if device.platform == "cpu":
        return cpu_nominal
    return default


def device_hbm_bandwidth(device: Optional[Any] = None) -> float:
    """HBM bytes/s of one chip. Env override TPUFLOW_HBM_BW."""
    return _device_spec(device, _HBM_BW, "TPUFLOW_HBM_BW",
                        cpu_nominal=50e9, default=1228e9)


def device_peak_flops(device: Optional[Any] = None) -> float:
    """Peak bf16 FLOP/s of one chip. Env override TPUFLOW_PEAK_FLOPS."""
    return _device_spec(device, _PEAK_BF16, "TPUFLOW_PEAK_FLOPS",
                        cpu_nominal=1e11, default=275e12)


def cost_analysis_of(compiled) -> dict:
    """XLA cost analysis of a ``Compiled`` (or ``Lowered``) object as
    ``{"flops", "bytes_accessed", "per_device"}``.

    Per-device lists are SUMMED across device shares (the whole
    program's work, with ``per_device`` recording how many shares went
    into it) instead of silently reading ``[0]`` — for an SPMD-
    partitioned step the old single-share read under-reported sharded
    programs by the device count. A backend that raises is no longer
    swallowed silently either: the failure is counted on the
    ``compile.cost_analysis_errors_total`` counter and an empty dict
    comes back."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        from tpuflow.obs.gauges import inc_counter

        inc_counter("compile.cost_analysis_errors_total")
        return {}
    shares = ca if isinstance(ca, list) else [ca]
    shares = [s for s in shares if s]
    if not shares:
        return {}
    return {
        "flops": float(sum(s.get("flops", 0.0) for s in shares)),
        "bytes_accessed": float(
            sum(s.get("bytes accessed", 0.0) for s in shares)
        ),
        "per_device": len(shares),
    }


def flops_of_compiled(compiled) -> float:
    """FLOPs from an already-compiled executable's XLA cost analysis
    (0.0 if the backend reports none), summed across per-device
    shares — see :func:`cost_analysis_of`."""
    return cost_analysis_of(compiled).get("flops", 0.0)


def arithmetic_intensity(flops: float,
                         bytes_accessed: float) -> Optional[float]:
    """FLOPs per byte moved — the x-axis of the roofline model. None
    when either input is missing/zero."""
    if not flops or not bytes_accessed:
        return None
    return float(flops) / float(bytes_accessed)


def roofline(flops: float, bytes_accessed: float,
             device: Optional[Any] = None) -> dict:
    """Roofline verdict for one executable against ONE chip's specs:
    ``arithmetic_intensity`` vs the ridge point
    ``peak_flops / hbm_bandwidth``. Below the ridge the program cannot
    reach peak FLOP/s no matter how good the kernels are — it is
    ``memory-bound`` and its attainable FLOP/s ceiling is
    ``AI × bandwidth``; above it, ``compute-bound`` with the chip's
    peak as the ceiling. Empty dict when the inputs are missing."""
    ai = arithmetic_intensity(flops, bytes_accessed)
    if ai is None:
        return {}
    peak = device_peak_flops(device)
    bw = device_hbm_bandwidth(device)
    ridge = peak / bw
    return {
        "arithmetic_intensity": ai,
        "ridge_flops_per_byte": ridge,
        "verdict": "memory-bound" if ai < ridge else "compute-bound",
        "attainable_flops_per_s": min(peak, ai * bw),
        "peak_flops_assumed": peak,
        "hbm_bandwidth_assumed": bw,
    }


def flops_of_jitted(jitted_fn, *args, **kwargs) -> float:
    """FLOPs of one invocation, from XLA cost analysis of the lowered
    executable. Returns 0.0 if the backend reports no estimate.

    This pays a compile: jax's AOT path does not populate the jit
    dispatch cache, so prefer compiling ONCE via ``lower().compile()``,
    reading :func:`flops_of_compiled`, and executing the compiled
    object — see LMTrainer.fit."""
    return flops_of_compiled(jitted_fn.lower(*args, **kwargs).compile())


def mfu(
    flops_per_step: float,
    step_time_s: float,
    n_chips: int = 1,
    device: Optional[Any] = None,
) -> float:
    """Model FLOP utilization in [0, 1]."""
    if step_time_s <= 0 or flops_per_step <= 0:
        return 0.0
    return flops_per_step / (step_time_s * n_chips * device_peak_flops(device))


def mobilenet_v2_flops(
    img_height: int = 224,
    img_width: int = 224,
    width_mult: float = 1.0,
    num_classes: int = 5,
    train: bool = True,
) -> float:
    """Analytic MobileNetV2 forward FLOPs per image (multiply-adds × 2),
    as a sanity cross-check against XLA's cost analysis. Backward for
    the frozen-backbone transfer model adds only the head, so
    train≈forward here; full fine-tuning would be ~3x forward."""
    from tpuflow.models.mobilenet_v2 import (
        _INVERTED_RESIDUAL_SETTINGS,
        make_divisible,
    )

    h, w = img_height // 2, img_width // 2
    stem = make_divisible(32 * width_mult)
    flops = 2 * h * w * stem * 3 * 9  # stem 3x3 conv
    in_ch = stem
    for t, c, n, s in _INVERTED_RESIDUAL_SETTINGS:
        out_ch = make_divisible(c * width_mult)
        for i in range(n):
            stride = s if i == 0 else 1
            hidden = in_ch * t
            if t != 1:
                flops += 2 * h * w * in_ch * hidden  # expand 1x1
            h2, w2 = h // stride, w // stride
            flops += 2 * h2 * w2 * hidden * 9  # depthwise 3x3
            flops += 2 * h2 * w2 * hidden * out_ch  # project 1x1
            h, w, in_ch = h2, w2, out_ch
    last = make_divisible(1280 * max(1.0, width_mult))
    flops += 2 * h * w * in_ch * last
    flops += 2 * last * num_classes  # head dense
    return float(flops)
