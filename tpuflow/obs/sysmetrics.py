"""Host + device metrics sampling (N11) — the Ganglia equivalent.

≙ the workshop's monitoring story: Ganglia dashboards for CPU/mem/
network (P1/04_monitoring_and_optimization.py:25-30). Sampled
programmatically (from /proc and the JAX device API) so the numbers can
be logged as run metrics alongside training instead of living in a
separate dashboard.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict


def _proc_meminfo() -> Dict[str, float]:
    out = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, rest = line.partition(":")
                v = rest.strip().split()
                if v:
                    out[k] = float(v[0]) * 1024  # kB -> bytes
    except OSError:
        pass
    return out


_last_cpu = None
_CPU_LOCK = threading.Lock()


def _cpu_percent() -> float:
    """System-wide CPU utilization since the previous call.

    The delta state (``_last_cpu``) is read-modify-written under a
    lock: concurrent samplers — the serve metrics thread and trainer
    logging both call :func:`sample_system_metrics` — would otherwise
    interleave on the module global and return garbage deltas (two
    threads both subtracting the SAME stale anchor, or one reading the
    tuple mid-replacement)."""
    global _last_cpu
    try:
        with open("/proc/stat") as f:
            parts = f.readline().split()[1:]
        vals = [float(x) for x in parts]
    except OSError:
        return 0.0
    idle = vals[3] + (vals[4] if len(vals) > 4 else 0)
    total = sum(vals)
    with _CPU_LOCK:
        prev = _last_cpu
        # monotonic guard: /proc/stat reads from two racing threads can
        # complete out of order; never step the anchor backwards
        if prev is None or total >= prev[0]:
            _last_cpu = (total, idle)
        if prev is None:
            return 0.0
        dt, di = total - prev[0], idle - prev[1]
    return 100.0 * (1 - di / dt) if dt > 0 else 0.0


def sample_system_metrics(include_devices: bool = True,
                          include_gauges: bool = True) -> Dict[str, float]:
    """One snapshot: host cpu/mem + per-device HBM, prefixed for
    run-metric logging (sys.* / device<i>.*). ``include_gauges`` merges
    the process-wide pushed gauges (tpuflow.obs.gauges — e.g. the
    serving runtime's serve.* occupancy/queue numbers), so one sampler
    covers pulled AND pushed sources."""
    m: Dict[str, float] = {"sys.cpu_percent": _cpu_percent(), "sys.time": time.time()}
    if include_gauges:
        from tpuflow.obs import memory
        from tpuflow.obs.gauges import snapshot_gauges

        # refresh the device-buffer ledger's mem.* gauges first so the
        # merged snapshot below carries them; a no-op (one dict
        # truthiness check) until something is tagged
        memory.maybe_update_gauges()
        m.update(snapshot_gauges())
    mem = _proc_meminfo()
    if mem:
        total = mem.get("MemTotal", 0.0)
        avail = mem.get("MemAvailable", 0.0)
        m["sys.mem_total_bytes"] = total
        m["sys.mem_used_bytes"] = total - avail
    try:
        m["sys.load_1m"] = os.getloadavg()[0]
    except OSError:
        pass
    if include_devices:
        import jax

        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                # explicit marker instead of silently omitting the
                # device: backends that return None (XLA:CPU) used to
                # be indistinguishable from a device with no keys —
                # "zero HBM pressure" and "no data" are different facts
                m[f"mem.device{d.id}.stats_unavailable"] = 1.0
                continue
            if "bytes_in_use" in stats:
                v = float(stats["bytes_in_use"])
                m[f"device{d.id}.hbm_in_use_bytes"] = v  # legacy key
                m[f"mem.device{d.id}.bytes_in_use"] = v
            if "bytes_limit" in stats:
                v = float(stats["bytes_limit"])
                m[f"device{d.id}.hbm_limit_bytes"] = v  # legacy key
                m[f"mem.device{d.id}.bytes_limit"] = v
    return m
