from tpuflow.obs.profiler import trace, annotate  # noqa: F401
from tpuflow.obs.mfu import (  # noqa: F401
    device_peak_flops,
    flops_of_jitted,
    mfu,
)
from tpuflow.obs.sysmetrics import sample_system_metrics  # noqa: F401
from tpuflow.obs.gauges import (  # noqa: F401
    clear_gauges,
    inc_counter,
    set_gauge,
    snapshot_gauges,
)
