# NOTE ``tpuflow.obs.trace`` is the span-tracer MODULE (ISSUE 4); the
# jax-profiler context manager formerly exported here under the same
# name stays available as ``profiler_trace`` and at its home,
# ``tpuflow.obs.profiler.trace``.
import tpuflow.obs.executables as executables  # noqa: F401
import tpuflow.obs.flight as flight  # noqa: F401
import tpuflow.obs.health as health  # noqa: F401
import tpuflow.obs.memory as memory  # noqa: F401
import tpuflow.obs.prom as prom  # noqa: F401
import tpuflow.obs.report as report  # noqa: F401
import tpuflow.obs.timeseries as timeseries  # noqa: F401
import tpuflow.obs.trace as trace  # noqa: F401
from tpuflow.obs.profiler import annotate  # noqa: F401
from tpuflow.obs.profiler import trace as profiler_trace  # noqa: F401
from tpuflow.obs.mfu import (  # noqa: F401
    device_peak_flops,
    flops_of_jitted,
    mfu,
)
from tpuflow.obs.sysmetrics import sample_system_metrics  # noqa: F401
from tpuflow.obs.gauges import (  # noqa: F401
    Histogram,
    clear_gauges,
    get_histogram,
    inc_counter,
    observe,
    register_histogram,
    set_gauge,
    snapshot_gauges,
)
