"""Windowed time-series over the gauge registry (ISSUE 5 tentpole).

:mod:`tpuflow.obs.gauges` histograms accumulate over the process
lifetime — O(1) memory, but after a long healthy run a regression moves
the p95/p99 only slowly (the cumulative-vs-windowed trade their
docstring documents). This module closes it WITHOUT giving up the
fixed-bucket representation: a :class:`SnapshotRing` captures every
registered histogram's raw bucket counts (plus gauges and counters) on
a fixed cadence, and a *windowed* percentile is computed by
DELTA-DIFFERENCING bucket counts between the live state and the
snapshot one window ago — exactly the rate()/increase() idiom a
Prometheus server applies to exported ``le`` buckets, done in-process
so ``/v1/metrics`` and ``snapshot_gauges`` can quote trailing-window
p50/p95/p99 directly.

Resolution is unchanged (same bucket grid, same log-interpolated
nearest-rank math — the documented ±~one-bucket error); the window
boundary is quantized to the snapshot cadence (a "60 s" window over a
10 s cadence covers 60±10 s of observations). The windowed min/max are
unknowable from count deltas, so interpolation clamps to the delta's
occupied bucket bounds instead of observed extremes — still within one
bucket of exact.

One process-wide default ring (`start`/`stop`/`tick`) feeds
``snapshot_gauges``'s primary percentile keys; serve and trainer
runtimes start it when their metrics surface comes up. Nothing here
runs unless started: an idle module costs one dict lookup per
``snapshot_gauges`` call.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional

from tpuflow.obs.gauges import (
    _HIST_BOUNDS,
    Histogram,
    counters as _counters,
    histograms as _histograms,
    scalar_gauges as _scalar_gauges,
)


def delta_histogram(cur: Dict[str, Any],
                    base: Optional[Dict[str, Any]]) -> Histogram:
    """Histogram holding the observations BETWEEN two
    :meth:`Histogram.state` captures (``base`` None = since process
    start). Bucket counts subtract (clamped at 0: a reset/replaced
    histogram under-reports until the baseline rotates out rather than
    going negative); min/max come from the delta's occupied buckets."""
    h = Histogram()
    bc = base["counts"] if base else None
    deltas = [
        max(0, c - (bc[i] if bc else 0))
        for i, c in enumerate(cur["counts"])
    ]
    h.counts = deltas
    h.n = sum(deltas)
    h.total = max(0.0, cur["total"] - (base["total"] if base else 0.0))
    lo_i = next((i for i, c in enumerate(deltas) if c), None)
    if lo_i is not None:
        hi_i = max(i for i, c in enumerate(deltas) if c)
        # window extremes are unknowable from count deltas: clamp to
        # bucket bounds (cumulative vmin/vmax still tighten the outer
        # buckets, whose bounds are the anchor values)
        h.vmin = (_HIST_BOUNDS[lo_i - 1] if lo_i > 0
                  else min(cur["vmin"], _HIST_BOUNDS[0]))
        h.vmax = (_HIST_BOUNDS[hi_i] if hi_i < len(_HIST_BOUNDS)
                  else max(cur["vmax"], _HIST_BOUNDS[-1]))
    return h


class SnapshotRing:
    """Fixed-interval snapshot ring over the gauge registry.

    Each :meth:`tick` appends ``{ts, hists: {name: state}, gauges,
    counters}``; the ring keeps ``capacity`` newest (default sized so
    the whole ring spans ~2x the window). Thread-safe; the clock is
    injectable for tests. Drive it manually (:meth:`tick`) or with
    :meth:`start`'s daemon thread."""

    def __init__(self, interval_s: float = 10.0, window_s: float = 60.0,
                 capacity: Optional[int] = None,
                 clock=time.time):
        if interval_s <= 0 or window_s <= 0:
            raise ValueError(
                f"interval_s/window_s must be > 0, got "
                f"{interval_s}/{window_s}"
            )
        self.interval_s = float(interval_s)
        self.window_s = float(window_s)
        if capacity is None:
            capacity = max(8, int(2 * window_s / interval_s) + 2)
        self.capacity = int(capacity)
        self.clock = clock
        self._lock = threading.Lock()
        self._snaps: List[Dict[str, Any]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ---- capture ----------------------------------------------------
    def tick(self) -> None:
        """Capture one snapshot of every registered histogram's raw
        state plus the scalar gauges/counters. Refreshes the
        device-buffer ledger's ``mem.*`` gauges first (ISSUE 7) — the
        ring's cadence is the one periodic heartbeat every long-lived
        process (serve frontend, prom exporter, trainers with a
        metrics port) already has, so the ledger needs no sampler of
        its own; a no-op until something is tagged."""
        try:
            from tpuflow.obs import memory as _memory

            _memory.maybe_update_gauges()
        except Exception:
            pass  # the ledger must never take the snapshot ring down
        snap = {
            "ts": self.clock(),
            "hists": {n: h.state() for n, h in _histograms().items()},
            "gauges": _scalar_gauges(),
            "counters": _counters(),
        }
        with self._lock:
            self._snaps.append(snap)
            if len(self._snaps) > self.capacity:
                del self._snaps[: len(self._snaps) - self.capacity]

    def start(self) -> None:
        """Spawn the fixed-interval ticker thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                self.tick()

        self._thread = threading.Thread(
            target=loop, name="tpuflow-metrics-ring", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def clear(self) -> None:
        with self._lock:
            self._snaps.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._snaps)

    # ---- windowed reads ---------------------------------------------
    def _baseline(self, window_s: Optional[float],
                  now: Optional[float]) -> Optional[Dict[str, Any]]:
        """The NEWEST snapshot at least ``window_s`` old (so the delta
        spans >= one window), else the oldest available, else None
        (ring empty → delta degenerates to the cumulative state)."""
        w = self.window_s if window_s is None else float(window_s)
        t = self.clock() if now is None else now
        with self._lock:
            if not self._snaps:
                return None
            older = [s for s in self._snaps if t - s["ts"] >= w]
            return older[-1] if older else self._snaps[0]

    def windowed(self, name: str, window_s: Optional[float] = None,
                 now: Optional[float] = None) -> Optional[Histogram]:
        """Histogram of roughly the last ``window_s`` of observations
        of registry histogram ``name`` (None if never registered)."""
        h = _histograms().get(name)
        if h is None:
            return None
        base = self._baseline(window_s, now)
        return delta_histogram(
            h.state(), (base or {}).get("hists", {}).get(name)
        )

    def windowed_percentiles(
        self, name: str, window_s: Optional[float] = None,
        pcts=(50.0, 95.0, 99.0),
    ) -> Dict[str, float]:
        """``{"p50": ...}`` over the trailing window (empty when the
        histogram is unknown or saw no samples in the window)."""
        h = self.windowed(name, window_s)
        return h.percentiles(pcts) if h is not None else {}

    def summaries(self, window_s: Optional[float] = None,
                  prefix: Optional[str] = None
                  ) -> Dict[str, Dict[str, Any]]:
        """Windowed percentiles + count + mean for every registered
        histogram (optionally only those under ``prefix`` — the
        delta-differencing is the expensive part, so callers filter
        BEFORE it, not after) — what ``snapshot_gauges`` merges as its
        primary percentile keys."""
        base = self._baseline(window_s, None)
        out: Dict[str, Dict[str, Any]] = {}
        for name, h in _histograms(prefix).items():
            d = delta_histogram(
                h.state(), (base or {}).get("hists", {}).get(name)
            )
            out[name] = {
                "percentiles": d.percentiles(),
                "count": d.n,
                "mean": (d.total / d.n) if d.n else math.nan,
            }
        return out

    def counter_rate(self, name: str,
                     window_s: Optional[float] = None) -> Optional[float]:
        """Per-second increase of counter ``name`` over the window
        (None without a baseline — a rate needs two points in time)."""
        base = self._baseline(window_s, None)
        if base is None or name not in base["counters"]:
            return None
        dt = self.clock() - base["ts"]
        if dt <= 0:
            return None
        cur = _counters().get(name, 0.0)
        return max(0.0, cur - base["counters"][name]) / dt

    def counter_increase(self, name: str,
                         window_s: Optional[float] = None,
                         now: Optional[float] = None) -> Optional[float]:
        """Absolute increase of counter ``name`` over the trailing
        window — the Prometheus ``increase()`` idiom, clamped at 0
        like :func:`delta_histogram` (a reset counter under-reports
        until the baseline rotates out rather than going negative).
        None when the ring has no baseline yet; a counter born inside
        the window counts in full (baseline value 0). The SLO
        evaluator's error-budget burn reads (ISSUE 20)."""
        base = self._baseline(window_s, now)
        if base is None:
            return None
        cur = _counters().get(name, 0.0)
        return max(0.0, cur - base["counters"].get(name, 0.0))

    # ---- export -----------------------------------------------------
    def export(self) -> Dict[str, Any]:
        """JSON-able dump of the ring — per-snapshot counters and
        histogram counts/totals (bucket arrays elided: the flight
        recorder wants the series shape, not 300 ints per hist per
        tick) plus the current windowed summaries. The run-scoped
        persistence payload (track/ store artifacts) and the flight
        recorder both write this."""
        with self._lock:
            snaps = list(self._snaps)
        series = [{
            "ts": s["ts"],
            "gauges": dict(s.get("gauges", {})),
            "counters": dict(s["counters"]),
            "hists": {
                n: {"n": st["n"], "total": st["total"]}
                for n, st in s["hists"].items()
            },
        } for s in snaps]
        summ = {
            n: {"percentiles": d["percentiles"], "count": d["count"],
                "mean": None if math.isnan(d["mean"]) else d["mean"]}
            for n, d in self.summaries().items()
        }
        return {
            "interval_s": self.interval_s,
            "window_s": self.window_s,
            "n_snapshots": len(series),
            "snapshots": series,
            "windowed": summ,
            # scalars + counters directly: the histogram summaries are
            # already in `windowed`, and snapshot_gauges would re-walk
            # every registry delta a second time for nothing
            "gauges": {**_scalar_gauges(), **_counters()},
        }


# ---- process-wide default ring --------------------------------------

_DEFAULT: Optional[SnapshotRing] = None
_DEFAULT_LOCK = threading.Lock()
# ensure()/release() refcount: metrics surfaces (serve frontend, prom
# exporter) acquire the ring for their lifetime; the LAST release of
# an ensure-created ring stops it, so no surface is ever left with a
# leaked ticker thread OR has a shared ring stopped out from under it
_REFS = 0
_OWNED = False  # ring was created through ensure() (refcount applies)


def default_ring() -> Optional[SnapshotRing]:
    """The process default ring (None until :func:`start`)."""
    return _DEFAULT


def start(interval_s: float = 10.0, window_s: float = 60.0,
          thread: bool = True) -> SnapshotRing:
    """Start (or return) the process default ring, un-refcounted — for
    drivers that own the process lifetime (tests; epoch-cadence
    trainers with ``thread=False`` driving :meth:`~SnapshotRing.tick`
    themselves). Surfaces with a shutdown path should pair
    :func:`ensure`/:func:`release` instead."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = SnapshotRing(interval_s, window_s)
        if thread:
            _DEFAULT.start()
        return _DEFAULT


def ensure(interval_s: float = 10.0, window_s: float = 60.0,
           thread: bool = True) -> SnapshotRing:
    """Acquire the default ring (creating it if needed) and hold a
    reference; pair with :func:`release`. Creation and the ownership
    decision happen atomically under one lock — two surfaces starting
    concurrently cannot both believe they created it."""
    global _DEFAULT, _REFS, _OWNED
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = SnapshotRing(interval_s, window_s)
            _OWNED = True
        if thread:
            _DEFAULT.start()
        _REFS += 1
        return _DEFAULT


def release() -> None:
    """Drop one :func:`ensure` reference; the last one out stops an
    ensure-created ring (a plain :func:`start` ring is never stopped
    here — its creator owns the process lifetime)."""
    global _REFS
    last = False
    with _DEFAULT_LOCK:
        _REFS = max(0, _REFS - 1)
        last = _REFS == 0 and _OWNED and _DEFAULT is not None
    if last:
        stop()


def stop() -> None:
    """Force-stop and drop the default ring regardless of references
    (test isolation; process shutdown)."""
    global _DEFAULT, _REFS, _OWNED
    with _DEFAULT_LOCK:
        if _DEFAULT is not None:
            _DEFAULT.stop()
            _DEFAULT = None
        _REFS = 0
        _OWNED = False


def windowed_summaries(prefix: Optional[str] = None
                       ) -> Dict[str, Dict[str, Any]]:
    """Default-ring windowed summaries, ``{}`` when no ring is ticking
    or it has no baseline yet — the ``snapshot_gauges`` fast path (one
    None check when the plane is idle)."""
    ring = _DEFAULT
    if ring is None or not len(ring):
        return {}
    return ring.summaries(prefix=prefix)


def windowed_counter_increase(name: str,
                              window_s: Optional[float] = None
                              ) -> Optional[float]:
    """Default-ring :meth:`SnapshotRing.counter_increase`; None when
    no ring is ticking (callers degrade to their cumulative view —
    PR 5 semantics)."""
    ring = _DEFAULT
    if ring is None or not len(ring):
        return None
    return ring.counter_increase(name, window_s)
