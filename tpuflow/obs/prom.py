"""Prometheus / OpenMetrics text exposition of the gauge registry
(ISSUE 5 tentpole) — the scrape-able half of the metrics plane.

``/v1/metrics`` keeps its ad-hoc JSON for humans and tests;
:func:`render` speaks the text exposition format (version 0.0.4) a
Prometheus server actually ingests: ``# HELP``/``# TYPE`` headers,
``gauge`` samples, monotone ``counter`` samples (``_total`` suffix
enforced), and classic histogram families — cumulative ``le``-labelled
``_bucket`` counts, ``_sum`` and ``_count`` — rendered straight from
:meth:`tpuflow.obs.gauges.Histogram.state`. Windowing is deliberately
NOT done here: Prometheus differences cumulative buckets itself
(``histogram_quantile(rate(..._bucket[5m]))``); the in-process windowed
view lives in :mod:`tpuflow.obs.timeseries`.

Per-replica metrics (ISSUE 8): registry names spelled
``<prefix>.replica<i>.<metric>`` — what the multi-replica router tier
gives each replica's ``ServeMetrics`` — are folded into ONE family per
metric with a ``replica="<i>"`` label, so an aggregating dashboard
queries ``sum by (replica) (rate(serve_ttft_ms_bucket[5m]))`` instead
of regex-joining N metric names.

Exposed bucket bounds are the shared fixed grid COARSENED by taking
every ``stride``-th bound (default 8 → exact powers of two of 1e-3,
~34 buckets instead of ~290): cumulative counts at surviving bounds
are exact (fine buckets nest inside coarse ones), Prometheus's own
interpolation error grows to the coarse bucket (~2x per bucket), and a
scrape stays a few KB per histogram.

Two servers can expose this text:

- the serve HTTP frontend's ``GET /metrics``
  (:mod:`tpuflow.serve.http`);
- :func:`start_exporter` — a standalone stdlib HTTP thread for
  processes with no serving frontend (trainers:
  ``TrainConfig.metrics_port``).
"""

from __future__ import annotations

import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Iterable, Optional

from tpuflow.obs.gauges import (
    bucket_bounds,
    counters,
    histograms,
    scalar_gauges,
)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

#: registry-name marker that becomes a ``replica="<i>"`` label: the
#: multi-replica serving tier (ISSUE 8) namespaces each replica's
#: metrics as ``serve.replica<i>.*`` so they don't clobber each other
#: in the shared registry — and the exposition folds them back into
#: ONE Prometheus family per metric, labelled per replica, which is
#: what a dashboard aggregating the tier actually wants to query.
_REPLICA_RE = re.compile(r"\.replica(\d+)(?=\.)")


def split_replica(name: str):
    """``serve.replica0.ttft_ms`` → ``("serve.ttft_ms", "0")``; names
    without the marker pass through as ``(name, None)``."""
    m = _REPLICA_RE.search(name)
    if m is None:
        return name, None
    return name[:m.start()] + name[m.end():], m.group(1)


#: SLO phase-attribution families (ISSUE 19): the per-phase member
#: histograms ``<prefix>.req_phase_ms.<phase>`` /
#: ``<prefix>.ttft_breakdown.<phase>`` fold into ONE family per
#: metric with a ``phase="<name>"`` label — composing with the replica
#: fold above, so a dashboard queries
#: ``sum by (phase) (rate(serve_req_phase_ms_bucket[5m]))`` across the
#: tier instead of regex-joining 6 metric names per replica.
_PHASE_RE = re.compile(r"\.(req_phase_ms|ttft_breakdown)\.(\w+)$")


def split_phase(name: str):
    """``serve.req_phase_ms.queue_wait`` →
    ``("serve.req_phase_ms", "queue_wait")``; names without a phase
    member suffix pass through as ``(name, None)``."""
    m = _PHASE_RE.search(name)
    if m is None:
        return name, None
    return name[:m.start()] + "." + m.group(1), m.group(2)


#: per-version metric cuts (ISSUE 20): the serve metrics record the
#: hot request-outcome families a second time under
#: ``<prefix>.version.<label>.<metric>`` so blue and green stay
#: comparable mid-rollout — the exposition folds the marker into a
#: ``version="<label>"`` label. Labels come from
#: :func:`tpuflow.serve.deploy.version_label` (``step<N>-<crc8hex>``)
#: whose alphabet is registry-name safe.
_VERSION_RE = re.compile(r"\.version\.([A-Za-z0-9_\-]+)(?=\.)")


def split_version(name: str):
    """``serve.version.step2-ab12cd34.ttft_ms`` →
    ``("serve.ttft_ms", "step2-ab12cd34")``; names without the marker
    pass through as ``(name, None)``."""
    m = _VERSION_RE.search(name)
    if m is None:
        return name, None
    return name[:m.start()] + name[m.end():], m.group(1)


def _label(rep, extra: str = "", phase=None, version=None) -> str:
    # label order is pinned (le, phase, replica, version): the golden
    # tests — and any operator's recording rules — match rendered
    # lines verbatim, so each new label slots in without moving the
    # existing ones (version appends after replica, ISSUE 20)
    parts = [p for p in (extra,
                         None if phase is None else f'phase="{phase}"',
                         None if rep is None else f'replica="{rep}"',
                         None if version is None
                         else f'version="{version}"')
             if p]
    return "{" + ",".join(parts) + "}" if parts else ""


def metric_name(name: str) -> str:
    """Dotted registry name → valid Prometheus metric name
    (``serve.ttft_ms`` → ``serve_ttft_ms``; leading digits guarded)."""
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not out or not _NAME_OK.match(out):
        out = "_" + out
    return out


def _fmt(v: float) -> str:
    """Sample value formatting: integers render bare (bucket counts),
    specials as +Inf/-Inf/NaN per the text format."""
    f = float(v)
    if f != f:
        return "NaN"
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render(prefix: Optional[str] = None, stride: int = 8) -> str:
    """The full exposition: every gauge, counter and histogram
    (optionally filtered to registry names under ``prefix``).
    ``stride`` coarsens the exposed bucket grid (1 = every fine
    bucket)."""
    if stride < 1:
        raise ValueError(f"stride must be >= 1, got {stride}")
    lines = []
    hists = histograms(prefix)
    cntrs = counters(prefix)
    # plain gauges only: histogram families are exported as buckets
    # below (their derived p50/p95 summary keys are a JSON-surface
    # convenience, re-derivable by any Prometheus consumer), and
    # snapshot_gauges would pay a windowed-delta walk per scrape just
    # to have its summary keys filtered back out here
    scalars = scalar_gauges(prefix)

    def _families(d: Dict[str, object]) -> "Dict[str, list]":
        # fold serve.replica<i>.* members into one family per metric,
        # keyed (replica_label, phase_label, version_label, value);
        # version-cut members (.version.<label>., ISSUE 20) and phase
        # members (req_phase_ms.<ph> / ttft_breakdown.<ph>) fold the
        # same way; plain names stay label-free
        fams: Dict[str, list] = {}
        for name in sorted(d):
            fam, rep = split_replica(name)
            fam, ver = split_version(fam)
            fam, ph = split_phase(fam)
            fams.setdefault(fam, []).append((rep, ph, ver, d[name]))
        return fams

    for fam, members in sorted(_families(scalars).items()):
        mn = metric_name(fam)
        lines.append(f"# HELP {mn} tpuflow gauge {fam}")
        lines.append(f"# TYPE {mn} gauge")
        for rep, ph, ver, v in members:
            lines.append(
                f"{mn}{_label(rep, phase=ph, version=ver)} {_fmt(v)}")
    for fam, members in sorted(_families(cntrs).items()):
        mn = metric_name(fam)
        if not mn.endswith("_total"):
            mn += "_total"
        lines.append(f"# HELP {mn} tpuflow counter {fam}")
        lines.append(f"# TYPE {mn} counter")
        for rep, ph, ver, v in members:
            lines.append(
                f"{mn}{_label(rep, phase=ph, version=ver)} {_fmt(v)}")
    bounds = bucket_bounds()
    # every stride-th bound STARTING AT THE FIRST: with the default
    # stride 8 on the 2**(1/8) grid that is exactly 1e-3 * 2^k — the
    # readable power-of-two labels the docstring promises. Cumulative
    # counts are exact at ANY subset of the fine bounds.
    coarse = list(range(0, len(bounds), stride))
    for fam, members in sorted(_families(hists).items()):
        mn = metric_name(fam)
        lines.append(f"# HELP {mn} tpuflow histogram {fam}")
        lines.append(f"# TYPE {mn} histogram")
        for rep, ph, ver, hist in members:
            st = hist.state()
            cum = 0
            i0 = 0
            for bi in coarse:
                cum += sum(st["counts"][i0:bi + 1])
                i0 = bi + 1
                # 6 significant digits: the repeated-multiplication
                # grid carries float dust (1e-3*2^1 accumulates to
                # 0.0020000000000000005) that would make every le
                # label 17 digits of noise in dashboards
                le = f'le="{bounds[bi]:.6g}"'
                lines.append(
                    f"{mn}_bucket"
                    f"{_label(rep, le, phase=ph, version=ver)} {cum}")
            cum += sum(st["counts"][i0:])
            le_inf = 'le="+Inf"'
            lines.append(
                f"{mn}_bucket"
                f"{_label(rep, le_inf, phase=ph, version=ver)} {cum}")
            lines.append(
                f"{mn}_sum{_label(rep, phase=ph, version=ver)}"
                f" {_fmt(st['total'])}")
            lines.append(
                f"{mn}_count{_label(rep, phase=ph, version=ver)}"
                f" {st['n']}")
    return "\n".join(lines) + "\n"


# ---- standalone exporter (trainers / exporter-only processes) -------

class _ExporterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "tpuflow-metrics/0.1"

    def log_message(self, fmt, *args):  # scrapers are chatty
        pass

    def do_GET(self):
        if self.path.split("?")[0] in ("/metrics", "/"):
            body = render(self.server.metrics_prefix).encode()
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
        elif self.path == "/healthz":
            body = b'{"ok": true}'
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        else:
            body = b"not found\n"
            self.send_response(404)
            self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class MetricsExporter(ThreadingHTTPServer):
    """Stdlib HTTP server exposing ``GET /metrics`` (+ a trivial
    ``/healthz`` liveness probe) for one process's registry."""

    daemon_threads = True

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 prefix: Optional[str] = None):
        super().__init__((host, port), _ExporterHandler)
        self.metrics_prefix = prefix

    @property
    def port(self) -> int:
        return self.server_address[1]

    def shutdown(self):
        with _STARTED_LOCK:  # so a later start_exporter(port) rebinds
            _STARTED.pop(getattr(self, "_requested_port", self.port),
                         None)
        if getattr(self, "_ring_ref", False):
            from tpuflow.obs import timeseries

            self._ring_ref = False
            timeseries.release()
        super().shutdown()


_STARTED: Dict[int, "MetricsExporter"] = {}
_STARTED_LOCK = threading.Lock()


def start_exporter(port: int = 0, host: str = "127.0.0.1",
                   prefix: Optional[str] = None,
                   start_ring: bool = True) -> MetricsExporter:
    """Start the exporter thread (``port=0`` = ephemeral, read
    ``.port`` back). Idempotent per REQUESTED port — a second fit()
    on the same ``TrainConfig.metrics_port`` reuses the running
    exporter instead of dying on EADDRINUSE, and repeated
    ``port=0`` requests reuse the process's one ephemeral exporter
    instead of leaking a server thread per fit. ``start_ring`` also
    starts the default timeseries ring so the windowed surfaces stay
    meaningful alongside the scrape. Stop with
    ``exporter.shutdown()``."""
    with _STARTED_LOCK:
        if port in _STARTED:
            cached = _STARTED[port]
            if (cached.server_address[0], cached.metrics_prefix) != (
                    host, prefix):
                # silently returning a server bound elsewhere (or
                # scoped differently) would hand the caller an
                # endpoint that does not do what they asked
                raise ValueError(
                    f"exporter for port {port} already running on "
                    f"{cached.server_address[0]} with prefix "
                    f"{cached.metrics_prefix!r}; shutdown() it first "
                    f"to rebind ({host!r}, {prefix!r})"
                )
            return cached
        server = MetricsExporter(host, port, prefix)
        server._requested_port = port
        _STARTED[port] = server
    if start_ring:
        from tpuflow.obs import timeseries

        timeseries.ensure()  # released in server.shutdown()
        server._ring_ref = True
    threading.Thread(target=server.serve_forever,
                     name="tpuflow-metrics-exporter",
                     daemon=True).start()
    return server
