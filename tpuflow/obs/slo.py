"""Multiwindow burn-rate SLO evaluation over snapshot-ring deltas
(ISSUE 20) — the decision layer of the metrics plane.

PR 19 left tpuflow with rich *signals* (phase vectors, windowed
percentiles, merged traces) and nothing that CONSUMES them: no code
answered "is this tier meeting its objectives right now?". This module
does, with zero new collection machinery: objectives are declared
against registry metric names and evaluated by DELTA-DIFFERENCING the
:class:`tpuflow.obs.timeseries.SnapshotRing` captures the metrics
plane already takes — the same ``increase()``/``histogram_quantile``
idiom a Prometheus server applies to the exported families, done
in-process so verdicts ride ``/v1/slo``, ``load_snapshot()`` and
flight bundles without a scrape loop.

Two objective kinds:

- **latency** — ``pP(metric)`` over a trailing window must stay under
  a threshold (``serve.ttft_ms:p95<2000@60``);
- **error budget** — the SRE burn-rate idiom: ``burn = (bad/total) /
  budget`` per window, evaluated over a SHORT and a LONG window
  simultaneously and breaching only when BOTH burn past the
  threshold. The short window makes detection fast; the long window
  keeps a brief blip from paging (a short spike alone recovers before
  the long window confirms it). ``serve.requests_failed_total/
  serve.requests_done_total<0.01@60/300x2`` reads "burning >2x the
  budget that would spend 1% of requests, confirmed on both the 60 s
  and 300 s windows".

Metric names FOLD the way the Prometheus exposition folds them: an
objective on ``serve.ttft_ms`` aggregates ``serve.replica<i>.ttft_ms``
(and any ``.version.<label>.`` cuts) across the in-process tier, so
one declaration covers a multi-replica frontend.

Pure host policy: lists, dicts and the registry — no jax, no device
work (pinned by a grep-guard test, the PR 7/8 idiom). The canary
scorer (:mod:`tpuflow.serve.canary`) and the roadmap's autoscaler read
the same evaluator.
"""

from __future__ import annotations

import math
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from tpuflow.obs.gauges import counters as _counters
from tpuflow.obs.gauges import histograms as _histograms
from tpuflow.obs.prom import split_replica, split_version
from tpuflow.obs.timeseries import SnapshotRing, delta_histogram


def fold_metric(name: str) -> str:
    """Registry name → the folded family name an objective matches:
    ``serve.replica0.version.step2-ab.ttft_ms`` → ``serve.ttft_ms``
    (exactly the Prometheus-exposition fold, minus the phase split —
    phase members are distinct metrics an objective names directly)."""
    fam, _ = split_replica(name)
    fam, _ = split_version(fam)
    return fam


# latency: [name=]metric:pP<T[ms]@W[s]
_LAT_RE = re.compile(
    r"^(?:(?P<name>[\w\-]+)=)?(?P<metric>[\w.\-]+):p(?P<pct>\d+(?:\.\d+)?)"
    r"<(?P<thresh>\d+(?:\.\d+)?)(?:ms)?@(?P<win>\d+(?:\.\d+)?)s?$")
# budget: [name=]bad[+bad2]/total[+total2]<B@Ws[s]/Wl[s][xF]
_BUD_RE = re.compile(
    r"^(?:(?P<name>[\w\-]+)=)?(?P<bad>[\w.\-+]+)/(?P<total>[\w.\-+]+)"
    r"<(?P<budget>\d*\.?\d+)@(?P<short>\d+(?:\.\d+)?)s?/"
    r"(?P<long>\d+(?:\.\d+)?)s?(?:x(?P<burn>\d+(?:\.\d+)?))?$")


def _qualify(metric: str, prefix: str) -> str:
    """Bare metric names (no dot) pick up the serve prefix —
    ``ttft_ms`` → ``serve.ttft_ms`` — so CLI declarations stay
    short; dotted names pass through untouched."""
    return metric if "." in metric else f"{prefix}.{metric}"


@dataclass(frozen=True)
class SLObjective:
    """One declared objective: ``(metric, window, threshold |
    error-budget)``. Latency objectives set ``threshold_ms``; budget
    objectives set ``budget`` + ``total_metrics`` (multiwindow:
    ``window_s`` short, ``long_window_s`` long, tripping only when
    both burn >= ``burn_threshold``)."""

    name: str
    metrics: Tuple[str, ...]            # latency metric, or bad counters
    window_s: float = 60.0
    # latency kind
    percentile: float = 95.0
    threshold_ms: Optional[float] = None
    # error-budget kind
    budget: Optional[float] = None      # allowed bad fraction
    total_metrics: Tuple[str, ...] = field(default_factory=tuple)
    long_window_s: Optional[float] = None
    burn_threshold: float = 1.0

    @property
    def kind(self) -> str:
        return "latency" if self.threshold_ms is not None else "budget"

    @staticmethod
    def parse(spec: str, prefix: str = "serve") -> "SLObjective":
        """Compact declaration grammar (the ``--slo`` CLI syntax)::

            [name=]metric:pP<THRESH[ms]@WINDOW[s]
            [name=]bad[+bad]/total[+total]<BUDGET@SHORT[s]/LONG[s][xBURN]

        e.g. ``ttft=serve.ttft_ms:p95<2000@60`` or
        ``errors=requests_failed_total/requests_done_total<0.01@60/300x2``.
        Bare metric names take the ``serve.`` prefix."""
        s = spec.strip()
        m = _LAT_RE.match(s)
        if m:
            metric = _qualify(m.group("metric"), prefix)
            return SLObjective(
                name=m.group("name") or metric.rsplit(".", 1)[-1],
                metrics=(metric,),
                window_s=float(m.group("win")),
                percentile=float(m.group("pct")),
                threshold_ms=float(m.group("thresh")),
            )
        m = _BUD_RE.match(s)
        if m:
            bad = tuple(_qualify(b, prefix)
                        for b in m.group("bad").split("+"))
            total = tuple(_qualify(t, prefix)
                          for t in m.group("total").split("+"))
            return SLObjective(
                name=m.group("name") or "budget",
                metrics=bad,
                total_metrics=total,
                window_s=float(m.group("short")),
                long_window_s=float(m.group("long")),
                budget=float(m.group("budget")),
                burn_threshold=float(m.group("burn") or 1.0),
            )
        raise ValueError(
            f"unparseable SLO spec {spec!r} — expected "
            f"'[name=]metric:pP<T@W' (latency) or "
            f"'[name=]bad/total<B@Ws/Wl[xF]' (error budget)")


def default_objectives(prefix: str = "serve") -> List[SLObjective]:
    """The stock serving objectives ``--slo default`` installs: TTFT
    and ITL p95 ceilings plus a request error budget (failure
    terminals + transfer fallbacks over completions) burned on
    60 s / 300 s windows."""
    return [
        SLObjective.parse(f"ttft={prefix}.ttft_ms:p95<2000@60"),
        SLObjective.parse(f"itl={prefix}.itl_ms:p95<200@60"),
        SLObjective.parse(
            f"errors={prefix}.requests_failed_total"
            f"+{prefix}.kv_transfer_failures_total"
            f"/{prefix}.requests_done_total"
            f"+{prefix}.requests_failed_total<0.01@60/300x1"),
    ]


class SLOEvaluator:
    """Evaluate objectives against the live registry + a snapshot
    ring.

    ``ring=None`` reads the process default ring
    (:func:`tpuflow.obs.timeseries.default_ring`); with no ring at all
    the windows degrade to cumulative-since-start (PR 5 semantics) and
    the report says so per objective (``windowed: false``).
    :meth:`report` caches for ``cache_s`` so hot surfaces
    (``load_snapshot``, flight providers) can quote verdicts without
    paying a delta walk per call; :meth:`evaluate` always recomputes.
    The clock is injectable for virtual-clock tests and benches."""

    def __init__(self, objectives: List[SLObjective], *,
                 ring: Optional[SnapshotRing] = None,
                 clock=time.time, cache_s: float = 5.0):
        if not objectives:
            raise ValueError("SLOEvaluator needs at least one objective")
        self.objectives = list(objectives)
        self._ring = ring
        self.clock = clock
        self.cache_s = float(cache_s)
        self._lock = threading.Lock()
        self._cache: Optional[Dict[str, Any]] = None
        self._cache_t = -math.inf

    # ---- windowed reads (fold-aware) --------------------------------
    def _the_ring(self) -> Optional[SnapshotRing]:
        if self._ring is not None:
            return self._ring
        from tpuflow.obs import timeseries

        ring = timeseries.default_ring()
        return ring if (ring is not None and len(ring)) else None

    def _baseline(self, ring, window_s: float, now: float):
        if ring is None:
            return None
        return ring._baseline(window_s, now)

    def _windowed_hist(self, ring, metric: str, window_s: float,
                       now: float):
        """Sum of windowed deltas across every registry histogram that
        folds to ``metric`` (replica/version members of one family);
        None when no histogram matches."""
        base = self._baseline(ring, window_s, now)
        agg = None
        for name, h in _histograms().items():
            if fold_metric(name) != metric:
                continue
            d = delta_histogram(
                h.state(), (base or {}).get("hists", {}).get(name))
            if agg is None:
                agg = d
            else:
                agg.merge(d)
        return agg

    def _windowed_counter(self, ring, metrics: Tuple[str, ...],
                          window_s: float, now: float) -> float:
        """Summed windowed increase across every registry counter that
        folds to one of ``metrics`` (clamped at 0 per member — the
        counter-reset idiom)."""
        base = self._baseline(ring, window_s, now)
        bc = (base or {}).get("counters", {})
        tot = 0.0
        for name, v in _counters().items():
            if fold_metric(name) in metrics:
                tot += max(0.0, float(v) - float(bc.get(name, 0.0)))
        return tot

    # ---- evaluation -------------------------------------------------
    def _eval_latency(self, ring, o: SLObjective, now: float,
                      windowed: bool) -> Dict[str, Any]:
        h = self._windowed_hist(ring, o.metrics[0], o.window_s, now)
        v: Dict[str, Any] = {
            "name": o.name, "kind": "latency", "metric": o.metrics[0],
            "percentile": o.percentile, "threshold_ms": o.threshold_ms,
            "window_s": o.window_s, "windowed": windowed,
        }
        n = h.n if h is not None else 0
        if n == 0:
            # a window with no traffic can't breach a latency SLO —
            # but the verdict says it judged nothing
            v.update(ok=True, value_ms=None, count=0, margin=None,
                     insufficient_data=True)
            return v
        pct = h.percentile(o.percentile)
        ok = pct <= o.threshold_ms
        v.update(
            ok=bool(ok), value_ms=round(float(pct), 3), count=int(n),
            margin=round((o.threshold_ms - pct) / o.threshold_ms, 4),
        )
        return v

    def _eval_budget(self, ring, o: SLObjective, now: float,
                     windowed: bool) -> Dict[str, Any]:
        long_w = o.long_window_s or 5 * o.window_s

        def burn(w: float):
            bad = self._windowed_counter(ring, o.metrics, w, now)
            tot = self._windowed_counter(ring, o.total_metrics, w, now)
            rate = (bad / tot) if tot else 0.0
            return (rate / o.budget if o.budget else math.inf,
                    bad, tot)

        b_short, bad_s, tot_s = burn(o.window_s)
        b_long, bad_l, tot_l = burn(long_w)
        # multiwindow AND: the short window detects fast, the long
        # window confirms it isn't a blip — the binding quantity is
        # the SMALLER burn
        binding = min(b_short, b_long)
        ok = binding < o.burn_threshold
        v: Dict[str, Any] = {
            "name": o.name, "kind": "budget",
            "bad_metrics": list(o.metrics),
            "total_metrics": list(o.total_metrics),
            "budget": o.budget, "burn_threshold": o.burn_threshold,
            "window_s": o.window_s, "long_window_s": long_w,
            "windowed": windowed,
            "burn_short": round(b_short, 4),
            "burn_long": round(b_long, 4),
            "bad_short": bad_s, "total_short": tot_s,
            "bad_long": bad_l, "total_long": tot_l,
            "ok": bool(ok),
            "margin": round(
                (o.burn_threshold - binding) / o.burn_threshold, 4),
        }
        if tot_s == 0 and tot_l == 0:
            v["insufficient_data"] = True
        return v

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Recompute every objective's verdict: ``{ts, ok,
        objectives: [...]}`` with per-objective margins (positive =
        headroom as a fraction of the threshold)."""
        t = self.clock() if now is None else now
        ring = self._the_ring()
        windowed = ring is not None
        verdicts = []
        for o in self.objectives:
            if o.kind == "latency":
                verdicts.append(self._eval_latency(ring, o, t, windowed))
            else:
                verdicts.append(self._eval_budget(ring, o, t, windowed))
        report = {
            "ts": t,
            "ok": all(v["ok"] for v in verdicts),
            "objectives": verdicts,
        }
        with self._lock:
            self._cache = report
            self._cache_t = t
        return report

    def report(self, max_age_s: Optional[float] = None) -> Dict[str, Any]:
        """The most recent evaluation, recomputed when older than
        ``max_age_s`` (default ``cache_s``) — what hot surfaces
        quote."""
        age = self.cache_s if max_age_s is None else float(max_age_s)
        now = self.clock()
        with self._lock:
            cached = self._cache
            fresh = cached is not None and (now - self._cache_t) <= age
        if fresh:
            return cached
        return self.evaluate(now)

    def verdicts_compact(self) -> Dict[str, Dict[str, Any]]:
        """``{name: {ok, margin}}`` — the load_snapshot-sized view."""
        rep = self.report()
        return {
            v["name"]: {"ok": v["ok"], "margin": v.get("margin")}
            for v in rep["objectives"]
        }


# ---- process default evaluator (the /v1/slo + flight surface) -------

_DEFAULT: Optional[SLOEvaluator] = None
_DEFAULT_LOCK = threading.Lock()


def install(evaluator: SLOEvaluator) -> SLOEvaluator:
    """Make ``evaluator`` the process default: ``/v1/slo`` serves its
    report, ``load_snapshot()`` quotes its compact verdicts, and every
    flight bundle captures an ``slo.json`` section. Last install
    wins."""
    global _DEFAULT
    from tpuflow.obs import flight

    with _DEFAULT_LOCK:
        _DEFAULT = evaluator
    flight.add_provider("slo", lambda: (
        _DEFAULT.report() if _DEFAULT is not None else None))
    return evaluator


def uninstall() -> None:
    global _DEFAULT
    from tpuflow.obs import flight

    with _DEFAULT_LOCK:
        _DEFAULT = None
    flight.remove_provider("slo")


def default_evaluator() -> Optional[SLOEvaluator]:
    return _DEFAULT


# ---- text rendering (cli.obs slo-report) ----------------------------

def format_slo_report(report: Dict[str, Any]) -> str:
    """One objective per row: verdict, value vs threshold, margin —
    the ``cli.obs slo-report`` renderer (beside ``trace-report``)."""
    lines = [f"SLO report  ts={report.get('ts', 0):.3f}  "
             f"overall={'OK' if report.get('ok') else 'BREACH'}"]
    for v in report.get("objectives", []):
        mark = "ok " if v.get("ok") else "FAIL"
        extra = " (no data)" if v.get("insufficient_data") else ""
        win = ("" if v.get("windowed", True)
               else " [cumulative: no ring]")
        if v.get("kind") == "latency":
            val = v.get("value_ms")
            val_s = "-" if val is None else f"{val:.1f}ms"
            lines.append(
                f"  [{mark}] {v['name']:<12} p{v['percentile']:g} "
                f"{v['metric']} = {val_s} "
                f"(< {v['threshold_ms']:g}ms @ {v['window_s']:g}s, "
                f"margin {_fmt_margin(v.get('margin'))})"
                f"{extra}{win}")
        else:
            lines.append(
                f"  [{mark}] {v['name']:<12} burn "
                f"{v.get('burn_short', 0):.2f}x/{v.get('burn_long', 0):.2f}x "
                f"(budget {v['budget']:g} @ {v['window_s']:g}s/"
                f"{v['long_window_s']:g}s, trip >= "
                f"{v['burn_threshold']:g}x, "
                f"margin {_fmt_margin(v.get('margin'))})"
                f"{extra}{win}")
    return "\n".join(lines)


def _fmt_margin(m) -> str:
    return "-" if m is None else f"{m * 100:+.1f}%"
