"""Step-time breakdown: where did the wall clock go? (ISSUE 4)

Turns host spans (live from :mod:`tpuflow.obs.trace`, or re-loaded from
an exported Chrome trace) into the question the ROADMAP north star
actually asks — host-dispatch vs device vs data-wait fractions of a
training run, and queue/prefill/decode fractions of a served request.
Instrumentation sites tag every span with a ``phase`` attr
(``data_wait`` / ``dispatch`` / ``device`` / ``compile`` /
``checkpoint`` / ``eval`` / ``prefill`` / ``decode``); the report
aggregates by phase over the capture window.

Also the ONE chrome-trace loader in the repo:
:func:`load_trace_events` reads both this repo's span exports
(:func:`tpuflow.obs.trace.export_chrome_trace`) and ``jax.profiler``
capture directories (``**/*.trace.json.gz``) — tools/trace_top_ops.py
parses XLA op events through it instead of keeping its own copy.

CLI surface: ``python -m tpuflow.cli.obs trace/report <file-or-dir>``.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from typing import Any, Dict, List, Optional

# canonical phase order for reports (anything else lands under its own
# name; uninstrumented wall time lands under "untracked")
PHASES = ("data_wait", "dispatch", "device", "compile", "checkpoint",
          "eval", "prefill", "decode", "queue")


# ---- chrome-trace loading (shared with tools/trace_top_ops.py) ------

def find_trace_json(trace_dir: str) -> Optional[str]:
    """Newest chrome-trace file under a directory: a ``jax.profiler``
    ``*.trace.json.gz`` capture, or a plain ``*.json`` span export."""
    hits = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                  recursive=True)
        + glob.glob(os.path.join(trace_dir, "*.json")),
        key=os.path.getmtime,
    )
    return hits[-1] if hits else None


def load_trace_events(path: str) -> List[Dict[str, Any]]:
    """``traceEvents`` list from a chrome-trace JSON: a file (.json or
    .trace.json.gz) or a directory to search (newest capture wins).
    Returns [] when nothing is found."""
    if os.path.isdir(path):
        found = find_trace_json(path)
        if found is None:
            return []
        path = found
    opener = gzip.open if path.endswith(".gz") else open
    try:
        with opener(path, "rt") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError, EOFError):
        return []
    if isinstance(doc, dict):
        return doc.get("traceEvents", []) or []
    return doc if isinstance(doc, list) else []


def spans_from_events(events: List[Dict[str, Any]]
                      ) -> List[Dict[str, Any]]:
    """Complete (``ph: "X"``) events → span dicts ``{name, dur_ms,
    ts_us, tid, thread, attrs}`` — the inverse of
    :func:`tpuflow.obs.trace.export_chrome_trace` (lossy only in the
    ns-resolution tail)."""
    tnames: Dict[Any, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tnames[e.get("tid")] = e.get("args", {}).get("name", "")
    out = []
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        out.append({
            "name": str(e.get("name", "")),
            "dur_ms": float(e["dur"]) / 1e3,
            "ts_us": float(e.get("ts", 0.0)),
            "tid": e.get("tid"),
            "thread": tnames.get(e.get("tid"), ""),
            "attrs": e.get("args", {}) or {},
        })
    return out


def _live_spans() -> List[Dict[str, Any]]:
    from tpuflow.obs.trace import snapshot

    spans = snapshot()
    return [{
        "name": s["name"], "dur_ms": s["dur_ms"],
        "ts_us": s["t0_ns"] / 1e3, "tid": s["tid"],
        "thread": s["thread"], "attrs": s["attrs"],
    } for s in spans]


# ---- the breakdown --------------------------------------------------

def step_breakdown(spans: Optional[List[Dict[str, Any]]] = None,
                   prefix: Optional[str] = None) -> Dict[str, Any]:
    """Aggregate spans into per-phase totals and fractions of the
    capture window.

    ``spans``: dicts from :func:`spans_from_events` / :func:`_live_spans`
    (None = the live tracer ring). ``prefix`` restricts to span names
    under it (e.g. ``"train."``). Fractions are of the WALL window
    (first span start → last span end) and are computed from each
    phase's interval UNION, not its summed durations: a serving
    capture has many concurrent requests whose queue spans overlap in
    wall time (64 requests queued for 2s is 128s of span-time inside a
    2s window), and summed durations would print 6400% — the union
    says "some request was queued during X% of the window", which is
    the honest wall-attribution. The summed span-time still ships as
    ``ms`` (it IS the right number for single-threaded train loops and
    for cost accounting); ``frac`` uses the union coverage.
    Instrumentation sites put the ``phase`` attr ONLY on leaf work
    spans (dispatch calls, host batch pulls, blocking fetches) —
    wrapper spans (``train.epoch``, ``serve.request``) carry none — so
    only phased spans enter the fraction table, and the window not
    covered by ANY phased span is reported as ``untracked``. When NO
    span carries a phase (a generic capture), everything aggregates by
    span name instead.
    """
    if spans is None:
        spans = _live_spans()
    if prefix is not None:
        spans = [s for s in spans if s["name"].startswith(prefix)]
    if not spans:
        return {"total_ms": 0.0, "phases": {}, "n_spans": 0}
    t0 = min(s["ts_us"] for s in spans)
    t1 = max(s["ts_us"] + s["dur_ms"] * 1e3 for s in spans)
    total_ms = (t1 - t0) / 1e3
    phased = [s for s in spans if s["attrs"].get("phase")]
    keyed = (
        [(s["attrs"]["phase"], s) for s in phased] if phased
        else [(s["name"], s) for s in spans]
    )
    by_phase: Dict[str, float] = {}
    counts: Dict[str, int] = {}
    intervals: Dict[str, List] = {}
    for key, s in keyed:
        by_phase[key] = by_phase.get(key, 0.0) + s["dur_ms"]
        counts[key] = counts.get(key, 0) + 1
        intervals.setdefault(key, []).append(
            (s["ts_us"], s["ts_us"] + s["dur_ms"] * 1e3)
        )
    covered = {
        ph: _union_ms(iv) for ph, iv in intervals.items()
    }
    phases = {
        ph: {
            "ms": round(ms, 3),
            "frac": (round(covered[ph] / total_ms, 4)
                     if total_ms > 0 else 0.0),
            "n": counts[ph],
        }
        for ph, ms in sorted(by_phase.items(), key=lambda kv: -kv[1])
    }
    if phased:
        tracked = _union_ms(
            [iv for ivs in intervals.values() for iv in ivs]
        )
        if total_ms > tracked:
            rest = total_ms - tracked
            phases["untracked"] = {
                "ms": round(rest, 3),
                "frac": round(rest / total_ms, 4),
                "n": 0,
            }
    return {
        "total_ms": round(total_ms, 3),
        "phases": phases,
        "n_spans": len(spans),
    }


def _union_ms(intervals: List) -> float:
    """Total length (ms) of the union of (start_us, end_us) intervals."""
    if not intervals:
        return 0.0
    out = 0.0
    cur_s = cur_e = None
    for s, e in sorted(intervals):
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                out += cur_e - cur_s
            cur_s, cur_e = s, e
        elif e > cur_e:
            cur_e = e
    out += cur_e - cur_s
    return out / 1e3


def format_report(bd: Dict[str, Any]) -> str:
    """Human-readable table of a :func:`step_breakdown` result."""
    if not bd.get("phases"):
        return "no spans captured (is the tracer enabled?)"
    lines = [
        f"step-time breakdown over {bd['total_ms'] / 1e3:.3f} s window "
        f"({bd['n_spans']} spans):"
    ]
    for ph, rec in bd["phases"].items():
        lines.append(
            f"  {ph:<16} {rec['ms'] / 1e3:8.3f} s  "
            f"{100 * rec['frac']:5.1f}%  (n={rec['n']})"
        )
    return "\n".join(lines)


# span-name → SLO phase for the tier timeline's attribution footer
# (ISSUE 19). Router- and worker-side spans that cover the same wall
# interval (router.prefill wraps the worker's serve.prefill_join) land
# in the SAME phase, and the footer unions intervals per phase, so the
# overlap does not double-count.
_TIER_PHASE = {
    "serve.queue": "queue_wait",
    "router.prefill": "prefill",
    "serve.prefill_join": "prefill",
    "router.transfer": "transfer",
    "router.pull": "transfer",
    "serve.transfer_land": "transfer",
    "serve.decode_segment": "decode",
}


def tier_timeline(trace: Dict[str, Any], width: int = 40) -> str:
    """Render a merged tier trace (the ``/v1/trace/<id>`` payload /
    ``Router.tier_trace`` result) as a per-phase text timeline: one row
    per span in offset-corrected start order, indented by parent
    nesting and tagged with its source process, a proportional bar over
    the request's wall window, and a phase-attribution footer (interval
    union per SLO phase, so parent/child overlap is not double-counted).
    """
    spans = list(trace.get("spans") or ())
    if not spans:
        return f"tier trace {trace.get('id')}: no spans (sampled out?)"
    durs = [s for s in spans if not s.get("instant")]
    insts = [s for s in spans if s.get("instant")]
    t0 = min(float(s["start_s"]) for s in spans)
    t1 = max(
        (float(s["start_s"]) + float(s.get("dur_ms") or 0.0) / 1e3
         for s in spans),
        default=t0,
    )
    e2e_ms = max((t1 - t0) * 1e3, 1e-9)
    by_id = {s["span_id"]: s for s in durs
             if s.get("span_id") is not None}

    def depth(s: Dict[str, Any]) -> int:
        d, seen = 0, set()
        while s.get("parent_id") in by_id and s["parent_id"] not in seen:
            seen.add(s["parent_id"])
            s = by_id[s["parent_id"]]
            d += 1
        return d

    srcs = sorted({str(s.get("source") or "?") for s in spans})
    off = trace.get("clock_offset_s") or {}
    hdr = (f"tier trace {trace.get('id')} — {len(srcs)} source"
           f"{'s' if len(srcs) != 1 else ''} ({', '.join(srcs)}) — "
           f"{len(durs)} spans + {len(insts)} events, "
           f"e2e {e2e_ms:.1f} ms")
    lines = [hdr]
    if off:
        lines.append("  clock offsets vs router: " + ", ".join(
            f"{k}={v * 1e3:+.3f} ms" for k, v in sorted(off.items())))
    sw = max(len(s) for s in srcs)
    for s in spans:
        start_ms = (float(s["start_s"]) - t0) * 1e3
        name = ("  " * depth(s) + s["name"]) if not s.get("instant") \
            else ("  " + s["name"])
        if s.get("instant"):
            pos = min(width - 1, int(width * start_ms / e2e_ms))
            bar = " " * pos + "·"
            tail = f"@{start_ms:9.3f} ms"
        else:
            dur = float(s.get("dur_ms") or 0.0)
            b0 = min(width - 1, int(width * start_ms / e2e_ms))
            b1 = min(width, max(b0 + 1,
                                int(width * (start_ms + dur) / e2e_ms)))
            bar = " " * b0 + "=" * (b1 - b0)
            tail = f"@{start_ms:9.3f} ms  {dur:9.3f} ms"
        lines.append(f"  {str(s.get('source') or '?'):<{sw}} "
                     f"|{bar:<{width}}| {tail}  {name}")
    phases: Dict[str, List] = {}
    for s in durs:
        ph = _TIER_PHASE.get(s["name"]) or (s.get("attrs") or {}).get(
            "phase")
        if ph:
            us0 = float(s["start_s"]) * 1e6
            phases.setdefault(str(ph), []).append(
                (us0, us0 + float(s.get("dur_ms") or 0.0) * 1e3))
    if phases:
        lines.append("  phase attribution (interval union):")
        for ph, iv in sorted(phases.items(),
                             key=lambda kv: -_union_ms(kv[1])):
            ms = _union_ms(iv)
            lines.append(f"    {ph:<12} {ms:9.3f} ms  "
                         f"{100 * ms / e2e_ms:5.1f}%")
    return "\n".join(lines)


def top_spans(spans: Optional[List[Dict[str, Any]]] = None,
              top: int = 15) -> List[Dict[str, Any]]:
    """Per-name total/mean/count table, heaviest first — the host-span
    twin of tools/trace_top_ops' XLA-op table."""
    if spans is None:
        spans = _live_spans()
    agg: Dict[str, List[float]] = {}
    for s in spans:
        agg.setdefault(s["name"], []).append(s["dur_ms"])
    rows = [
        {
            "name": name,
            "total_ms": round(sum(ds), 3),
            "mean_ms": round(sum(ds) / len(ds), 3),
            "count": len(ds),
        }
        for name, ds in agg.items()
    ]
    rows.sort(key=lambda r: -r["total_ms"])
    return rows[:top]
