"""Process-wide gauge/counter registry — the push half of obs.

:func:`sample_system_metrics` (tpuflow.obs.sysmetrics) PULLS host and
device numbers at sample time; long-lived runtimes (the serving
scheduler, trainers with background staging) instead PUSH their
operational gauges here as they change, and any metrics consumer —
run-metric logging, the serve HTTP ``/v1/metrics`` endpoint, a test —
reads one merged snapshot. Names follow the sysmetrics dotted
convention (``serve.slot_occupancy``, ``serve.batch_efficiency``) so a
tracking store ingests both sources identically.

Thread-safe; values are plain floats (gauges overwrite, counters add).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

_LOCK = threading.Lock()
_GAUGES: Dict[str, float] = {}
_COUNTERS: Dict[str, float] = {}


def set_gauge(name: str, value: float) -> None:
    """Overwrite gauge ``name`` (last write wins)."""
    with _LOCK:
        _GAUGES[name] = float(value)


def inc_counter(name: str, by: float = 1.0) -> float:
    """Add ``by`` to counter ``name`` (created at 0); returns the new
    value."""
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0.0) + float(by)
        return _COUNTERS[name]


def snapshot_gauges(prefix: Optional[str] = None) -> Dict[str, float]:
    """One merged dict of every gauge and counter (optionally filtered
    to names starting with ``prefix``)."""
    with _LOCK:
        merged = dict(_GAUGES)
        merged.update(_COUNTERS)
    if prefix is not None:
        merged = {k: v for k, v in merged.items() if k.startswith(prefix)}
    return merged


def clear_gauges(prefix: Optional[str] = None) -> None:
    """Drop gauges/counters (all, or those under ``prefix``) — test
    isolation and runtime restarts."""
    with _LOCK:
        if prefix is None:
            _GAUGES.clear()
            _COUNTERS.clear()
        else:
            for d in (_GAUGES, _COUNTERS):
                for k in [k for k in d if k.startswith(prefix)]:
                    del d[k]
