"""Process-wide gauge/counter/histogram registry — the push half of obs.

:func:`sample_system_metrics` (tpuflow.obs.sysmetrics) PULLS host and
device numbers at sample time; long-lived runtimes (the serving
scheduler, trainers with background staging) instead PUSH their
operational numbers here as they change, and any metrics consumer —
run-metric logging, the serve HTTP ``/v1/metrics`` endpoint, a test —
reads one merged snapshot. Names follow the sysmetrics dotted
convention (``serve.slot_occupancy``, ``serve.batch_efficiency``) so a
tracking store ingests both sources identically.

Three primitives (ISSUE 4 added the third):

- **gauges** — ``set_gauge``: last write wins;
- **counters** — ``inc_counter``: monotonic adds;
- **histograms** — ``observe(name, value)``: FIXED log-spaced buckets
  (~9% per bucket over 1e-3..1e7, so latencies in ms and throughputs
  both fit), O(1) memory regardless of sample count, p50/p95/p99
  merged into every snapshot as ``<name>_p50`` etc. This is what
  :mod:`tpuflow.serve.metrics` percentiles ride on — one histogram
  implementation instead of per-module percentile math.

Thread-safe; values are plain floats.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, Iterable, Optional

_LOCK = threading.Lock()
_GAUGES: Dict[str, float] = {}
_COUNTERS: Dict[str, float] = {}
_HISTS: Dict[str, "Histogram"] = {}

# fixed bucket grid, shared by every Histogram: upper bounds growing by
# 2**(1/8) (~9.05%) from 1e-3 to past 1e7 — FIXED so histograms from
# different sources/processes merge by plain counter addition
_HIST_FACTOR = 2.0 ** 0.125
_HIST_BOUNDS: list = []
_b = 1e-3
while _b < 1e7:
    _HIST_BOUNDS.append(_b)
    _b *= _HIST_FACTOR
del _b


class Histogram:
    """Fixed-bucket histogram with interpolated percentiles.

    Memory is O(#buckets) forever — unlike a sample list there is no
    sliding window and no cap to tune; the trades are resolution (a
    percentile is exact to its bucket: ±~4.5% around the log-bucket
    center, tightened by log-linear interpolation within the bucket
    and clamped to the observed min/max) and RECENCY: counts are
    cumulative over the histogram's lifetime, so after N observations
    a behavior change needs O(N·(1-p)) new samples to move p-th
    percentiles. The RECENCY half of that trade is closed by
    :mod:`tpuflow.obs.timeseries` (ISSUE 5): a snapshot ring captures
    :meth:`state` on a fixed cadence and delta-differences bucket
    counts between snapshots into *windowed* percentiles — the
    Prometheus counter idiom (the process accumulates, the consumer
    differences) done in-process, so no consumer has to. ``merge``
    adds another histogram's counts in — snapshot aggregation across
    sources; :meth:`reset` stays for callers that want a hard
    accumulation restart instead of a window.
    """

    __slots__ = ("counts", "n", "total", "vmin", "vmax", "_lock")

    def __init__(self):
        self.counts = [0] * (len(_HIST_BOUNDS) + 1)  # +1: overflow
        self.n = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        i = bisect.bisect_left(_HIST_BOUNDS, v)
        with self._lock:
            self.counts[i] += 1
            self.n += 1
            self.total += v
            if v < self.vmin:
                self.vmin = v
            if v > self.vmax:
                self.vmax = v

    def reset(self) -> None:
        """Drop all counts — start a fresh accumulation window."""
        with self._lock:
            self.counts = [0] * (len(_HIST_BOUNDS) + 1)
            self.n = 0
            self.total = 0.0
            self.vmin = math.inf
            self.vmax = -math.inf

    def state(self) -> Dict[str, object]:
        """Consistent copy of the raw accumulation state —
        ``{"counts": [...], "n": int, "total": float, "vmin": float,
        "vmax": float}`` — the unit the timeseries snapshot ring
        records and :mod:`tpuflow.obs.prom` renders as cumulative
        ``le`` buckets. ``counts[i]`` counts observations <=
        ``bucket_bounds()[i]`` exclusive-of-lower; the final slot is
        the overflow bucket."""
        with self._lock:
            return {"counts": list(self.counts), "n": self.n,
                    "total": self.total, "vmin": self.vmin,
                    "vmax": self.vmax}

    def merge(self, other: "Histogram") -> None:
        with other._lock:
            oc, on, ot = list(other.counts), other.n, other.total
            ovmin, ovmax = other.vmin, other.vmax
        with self._lock:
            self.counts = [a + b for a, b in zip(self.counts, oc)]
            self.n += on
            self.total += ot
            self.vmin = min(self.vmin, ovmin)
            self.vmax = max(self.vmax, ovmax)

    def __len__(self) -> int:
        return self.n

    def mean(self) -> Optional[float]:
        return self.total / self.n if self.n else None

    def percentile(self, p: float) -> Optional[float]:
        """Nearest-rank percentile, log-interpolated within its bucket
        and clamped to [observed min, observed max]. None when empty."""
        with self._lock:
            n = self.n
            if n == 0:
                return None
            counts = list(self.counts)
            vmin, vmax = self.vmin, self.vmax
        rank = max(0, min(n - 1, math.ceil(p / 100.0 * n) - 1))
        cum = 0
        for i, c in enumerate(counts):
            if cum + c > rank:
                lo = _HIST_BOUNDS[i - 1] if i > 0 else vmin
                hi = (_HIST_BOUNDS[i] if i < len(_HIST_BOUNDS) else vmax)
                if lo <= 0 or hi <= lo:
                    v = hi if hi > 0 else lo
                else:
                    f = (rank - cum + 0.5) / c
                    v = lo * (hi / lo) ** f  # log-linear within bucket
                return float(min(max(v, vmin), vmax))
            cum += c
        return float(vmax)  # pragma: no cover - unreachable

    def percentiles(self, pcts: Iterable[float] = (50.0, 95.0, 99.0)
                    ) -> Dict[str, float]:
        """``{"p50": ..., "p95": ..., "p99": ...}`` (empty when empty)
        — the same key format as :func:`tpuflow.serve.metrics.
        percentiles`."""
        out: Dict[str, float] = {}
        for p in pcts:
            v = self.percentile(p)
            if v is not None:
                out[f"p{p:g}"] = v
        return out


def set_gauge(name: str, value: float) -> None:
    """Overwrite gauge ``name`` (last write wins)."""
    with _LOCK:
        _GAUGES[name] = float(value)


def inc_counter(name: str, by: float = 1.0) -> float:
    """Add ``by`` to counter ``name`` (created at 0); returns the new
    value."""
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0.0) + float(by)
        return _COUNTERS[name]


def observe(name: str, value: float) -> None:
    """Record ``value`` into histogram ``name`` (created on first use).
    Snapshots surface it as ``<name>_p50/_p95/_p99/_count/_mean``."""
    with _LOCK:
        h = _HISTS.get(name)
        if h is None:
            h = _HISTS[name] = Histogram()
    h.observe(value)


def get_histogram(name: str) -> Optional[Histogram]:
    """The registered histogram (None if never observed)."""
    with _LOCK:
        return _HISTS.get(name)


def register_histogram(name: str, hist: Histogram) -> Histogram:
    """Adopt an externally-owned :class:`Histogram` into the registry
    under ``name`` (last registration wins) — how
    :class:`tpuflow.serve.metrics.ServeMetrics` publishes its latency
    histograms so the snapshot ring, the Prometheus exposition and
    ``snapshot_gauges`` all see ONE instance instead of a copy."""
    with _LOCK:
        _HISTS[name] = hist
    return hist


def histograms(prefix: Optional[str] = None) -> Dict[str, Histogram]:
    """Shallow copy of the histogram registry (live instances — treat
    as read-only via :meth:`Histogram.state`/percentiles)."""
    with _LOCK:
        items = dict(_HISTS)
    if prefix is not None:
        items = {k: v for k, v in items.items() if k.startswith(prefix)}
    return items


def bucket_bounds() -> list:
    """The shared fixed bucket upper bounds (ascending; observations
    above the last bound land in the overflow slot). Returned list is
    the module constant — do not mutate."""
    return _HIST_BOUNDS


def counters(prefix: Optional[str] = None) -> Dict[str, float]:
    """Copy of the counters alone (the Prometheus exposition needs to
    tell them apart from gauges; ``snapshot_gauges`` merges both)."""
    with _LOCK:
        out = dict(_COUNTERS)
    if prefix is not None:
        out = {k: v for k, v in out.items() if k.startswith(prefix)}
    return out


def scalar_gauges(prefix: Optional[str] = None) -> Dict[str, float]:
    """Copy of the plain gauges alone — consumers that already hold
    histogram summaries (the timeseries export) use this instead of
    re-deriving them through ``snapshot_gauges``."""
    with _LOCK:
        out = dict(_GAUGES)
    if prefix is not None:
        out = {k: v for k, v in out.items() if k.startswith(prefix)}
    return out


def snapshot_gauges(prefix: Optional[str] = None) -> Dict[str, float]:
    """One merged dict of every gauge, counter and histogram summary
    (optionally filtered to names starting with ``prefix``).

    Histogram percentiles are WINDOWED when the
    :mod:`tpuflow.obs.timeseries` default ring is ticking (trailing
    ``window_s`` of observations — the number a live dashboard wants),
    and fall back to the all-time cumulative values when it is not;
    the cumulative values are always present under a ``_cum`` suffix
    (``<name>_p50_cum``/``_count_cum``), so consumers that difference
    across scrapes keep their monotone series either way."""
    with _LOCK:
        merged = dict(_GAUGES)
        merged.update(_COUNTERS)
        hists = list(_HISTS.items())
    if prefix is not None:
        # filter BEFORE the windowed walk: delta-differencing every
        # registry histogram just to discard the keys is the waste
        # scalar_gauges/counters exist to avoid
        hists = [(k, v) for k, v in hists if k.startswith(prefix)]
    windowed = {}
    if hists:
        from tpuflow.obs import timeseries

        windowed = timeseries.windowed_summaries(prefix)
    for name, h in hists:
        cum_p = h.percentiles()
        win = windowed.get(name)
        # all-or-nothing per histogram: an EMPTY window (ring ticking,
        # no samples lately) falls back to the cumulative summary
        # WHOLESALE, so the primary keys never vanish on a quiet lull
        # and count/mean always describe the same samples as the
        # percentiles beside them
        use_win = bool(win and win["count"])
        for pk, pv in (win["percentiles"] if use_win else cum_p).items():
            merged[f"{name}_{pk}"] = round(pv, 3)
        for pk, pv in cum_p.items():
            merged[f"{name}_{pk}_cum"] = round(pv, 3)
        if use_win:
            merged[f"{name}_count"] = float(win["count"])
            merged[f"{name}_mean"] = round(win["mean"], 3)
        elif len(h):
            merged[f"{name}_count"] = float(len(h))
            merged[f"{name}_mean"] = round(h.mean(), 3)
        if len(h):
            merged[f"{name}_count_cum"] = float(len(h))
    if prefix is not None:
        merged = {k: v for k, v in merged.items() if k.startswith(prefix)}
    return merged


def clear_gauges(prefix: Optional[str] = None) -> None:
    """Drop gauges/counters/histograms (all, or those under
    ``prefix``) — test isolation and runtime restarts."""
    with _LOCK:
        if prefix is None:
            _GAUGES.clear()
            _COUNTERS.clear()
            _HISTS.clear()
        else:
            for d in (_GAUGES, _COUNTERS, _HISTS):
                for k in [k for k in d if k.startswith(prefix)]:
                    del d[k]
