"""Process-wide gauge/counter/histogram registry — the push half of obs.

:func:`sample_system_metrics` (tpuflow.obs.sysmetrics) PULLS host and
device numbers at sample time; long-lived runtimes (the serving
scheduler, trainers with background staging) instead PUSH their
operational numbers here as they change, and any metrics consumer —
run-metric logging, the serve HTTP ``/v1/metrics`` endpoint, a test —
reads one merged snapshot. Names follow the sysmetrics dotted
convention (``serve.slot_occupancy``, ``serve.batch_efficiency``) so a
tracking store ingests both sources identically.

Three primitives (ISSUE 4 added the third):

- **gauges** — ``set_gauge``: last write wins;
- **counters** — ``inc_counter``: monotonic adds;
- **histograms** — ``observe(name, value)``: FIXED log-spaced buckets
  (~9% per bucket over 1e-3..1e7, so latencies in ms and throughputs
  both fit), O(1) memory regardless of sample count, p50/p95/p99
  merged into every snapshot as ``<name>_p50`` etc. This is what
  :mod:`tpuflow.serve.metrics` percentiles ride on — one histogram
  implementation instead of per-module percentile math.

Thread-safe; values are plain floats.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, Iterable, Optional

_LOCK = threading.Lock()
_GAUGES: Dict[str, float] = {}
_COUNTERS: Dict[str, float] = {}
_HISTS: Dict[str, "Histogram"] = {}

# fixed bucket grid, shared by every Histogram: upper bounds growing by
# 2**(1/8) (~9.05%) from 1e-3 to past 1e7 — FIXED so histograms from
# different sources/processes merge by plain counter addition
_HIST_FACTOR = 2.0 ** 0.125
_HIST_BOUNDS: list = []
_b = 1e-3
while _b < 1e7:
    _HIST_BOUNDS.append(_b)
    _b *= _HIST_FACTOR
del _b


class Histogram:
    """Fixed-bucket histogram with interpolated percentiles.

    Memory is O(#buckets) forever — unlike a sample list there is no
    sliding window and no cap to tune; the trades are resolution (a
    percentile is exact to its bucket: ±~4.5% around the log-bucket
    center, tightened by log-linear interpolation within the bucket
    and clamped to the observed min/max) and RECENCY: counts are
    cumulative over the histogram's lifetime, so after N observations
    a behavior change needs O(N·(1-p)) new samples to move p-th
    percentiles. A long-lived server that wants windowed percentiles
    should :meth:`reset` on its scrape cadence (the Prometheus
    counter idiom: the scraper differences/rotates, the process
    accumulates) — or difference exported counts itself. ``merge``
    adds another histogram's counts in — snapshot aggregation across
    sources.
    """

    __slots__ = ("counts", "n", "total", "vmin", "vmax", "_lock")

    def __init__(self):
        self.counts = [0] * (len(_HIST_BOUNDS) + 1)  # +1: overflow
        self.n = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        i = bisect.bisect_left(_HIST_BOUNDS, v)
        with self._lock:
            self.counts[i] += 1
            self.n += 1
            self.total += v
            if v < self.vmin:
                self.vmin = v
            if v > self.vmax:
                self.vmax = v

    def reset(self) -> None:
        """Drop all counts — start a fresh accumulation window."""
        with self._lock:
            self.counts = [0] * (len(_HIST_BOUNDS) + 1)
            self.n = 0
            self.total = 0.0
            self.vmin = math.inf
            self.vmax = -math.inf

    def merge(self, other: "Histogram") -> None:
        with other._lock:
            oc, on, ot = list(other.counts), other.n, other.total
            ovmin, ovmax = other.vmin, other.vmax
        with self._lock:
            self.counts = [a + b for a, b in zip(self.counts, oc)]
            self.n += on
            self.total += ot
            self.vmin = min(self.vmin, ovmin)
            self.vmax = max(self.vmax, ovmax)

    def __len__(self) -> int:
        return self.n

    def mean(self) -> Optional[float]:
        return self.total / self.n if self.n else None

    def percentile(self, p: float) -> Optional[float]:
        """Nearest-rank percentile, log-interpolated within its bucket
        and clamped to [observed min, observed max]. None when empty."""
        with self._lock:
            n = self.n
            if n == 0:
                return None
            counts = list(self.counts)
            vmin, vmax = self.vmin, self.vmax
        rank = max(0, min(n - 1, math.ceil(p / 100.0 * n) - 1))
        cum = 0
        for i, c in enumerate(counts):
            if cum + c > rank:
                lo = _HIST_BOUNDS[i - 1] if i > 0 else vmin
                hi = (_HIST_BOUNDS[i] if i < len(_HIST_BOUNDS) else vmax)
                if lo <= 0 or hi <= lo:
                    v = hi if hi > 0 else lo
                else:
                    f = (rank - cum + 0.5) / c
                    v = lo * (hi / lo) ** f  # log-linear within bucket
                return float(min(max(v, vmin), vmax))
            cum += c
        return float(vmax)  # pragma: no cover - unreachable

    def percentiles(self, pcts: Iterable[float] = (50.0, 95.0, 99.0)
                    ) -> Dict[str, float]:
        """``{"p50": ..., "p95": ..., "p99": ...}`` (empty when empty)
        — the same key format as :func:`tpuflow.serve.metrics.
        percentiles`."""
        out: Dict[str, float] = {}
        for p in pcts:
            v = self.percentile(p)
            if v is not None:
                out[f"p{p:g}"] = v
        return out


def set_gauge(name: str, value: float) -> None:
    """Overwrite gauge ``name`` (last write wins)."""
    with _LOCK:
        _GAUGES[name] = float(value)


def inc_counter(name: str, by: float = 1.0) -> float:
    """Add ``by`` to counter ``name`` (created at 0); returns the new
    value."""
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0.0) + float(by)
        return _COUNTERS[name]


def observe(name: str, value: float) -> None:
    """Record ``value`` into histogram ``name`` (created on first use).
    Snapshots surface it as ``<name>_p50/_p95/_p99/_count/_mean``."""
    with _LOCK:
        h = _HISTS.get(name)
        if h is None:
            h = _HISTS[name] = Histogram()
    h.observe(value)


def get_histogram(name: str) -> Optional[Histogram]:
    """The registered histogram (None if never observed)."""
    with _LOCK:
        return _HISTS.get(name)


def snapshot_gauges(prefix: Optional[str] = None) -> Dict[str, float]:
    """One merged dict of every gauge, counter and histogram summary
    (optionally filtered to names starting with ``prefix``)."""
    with _LOCK:
        merged = dict(_GAUGES)
        merged.update(_COUNTERS)
        hists = list(_HISTS.items())
    for name, h in hists:
        for pk, pv in h.percentiles().items():
            merged[f"{name}_{pk}"] = round(pv, 3)
        if len(h):
            merged[f"{name}_count"] = float(len(h))
            merged[f"{name}_mean"] = round(h.mean(), 3)
    if prefix is not None:
        merged = {k: v for k, v in merged.items() if k.startswith(prefix)}
    return merged


def clear_gauges(prefix: Optional[str] = None) -> None:
    """Drop gauges/counters/histograms (all, or those under
    ``prefix``) — test isolation and runtime restarts."""
    with _LOCK:
        if prefix is None:
            _GAUGES.clear()
            _COUNTERS.clear()
            _HISTS.clear()
        else:
            for d in (_GAUGES, _COUNTERS, _HISTS):
                for k in [k for k in d if k.startswith(prefix)]:
                    del d[k]
