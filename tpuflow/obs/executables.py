"""Compile/executable registry + recompile watchdog (ISSUE 7
tentpole) — the compile half of the memory-and-compile plane.

The spans (ISSUE 4) and metrics plane (ISSUE 5) observe *time*;
nothing observed *compilation* — the resource that silently eats
serving latency (every _LRU eviction is seconds of rebuild) and the
one whose pathologies (bucket-menu explosion, shape leaks retracing a
trainer step every call) look exactly like "the job got slow" until
someone diffs executable counts. This module is the one place every
compile the repo performs reports to:

- **registered jit sites** — :func:`registered_jit` wraps ``jax.jit``;
  every call site under ``tpuflow/`` routes through it (a grep-based
  tier-1 guard pins that). When the registry is DISABLED (default) the
  wrapper is a single flag read + delegation — the same near-zero
  contract as the tracer; when enabled, each call does one C-level
  ``_cache_size()`` read, and a size increase == a compile event:
  wall time (the miss call's wall — trace+compile+first dispatch),
  the argument shape signature, and per-site hit/miss counts are
  recorded. ``analyze='lower'`` additionally pays ONE retrace per
  compile to harvest XLA's pre-compile ``cost_analysis`` (FLOPs,
  bytes accessed → arithmetic intensity and a roofline verdict).
- **AOT registrations** — sites that already compile ahead-of-time for
  FLOPs accounting (the trainers' ``lower().compile()``) call
  :meth:`RegisteredJit.aot_compile` / :func:`register_compiled`
  instead, which captures the FULL picture from the compiled object:
  ``cost_analysis()`` (summed across device shares —
  :func:`tpuflow.obs.mfu.cost_analysis_of`), ``memory_analysis()``
  (temp/argument/output/alias bytes — the numbers that would have
  flagged the ISSUE 6 page-scatter copy), compile wall time. No extra
  compile is ever paid: registration reads what the site already built.
- **recompile watchdog** — the same registry key compiling across more
  than ``recompile_threshold`` DISTINCT argument-shape signatures
  (bucket-menu explosion, shape leaks — deliberate same-shape
  re-compiles across fresh fits don't count) trips the (ISSUE 5)
  watchdog with the offending shape signatures in the message; the
  trip latches into
  ``/readyz`` reasons and flight-recorder manifests exactly like a
  NaN or stall trip because it rides the same
  :func:`~tpuflow.obs.health.default_watchdog`. Trips only fire while
  the registry is ENABLED (armed by the serve CLI, by
  ``TrainConfig.watchdog``, by ``TPUFLOW_COMPILE_REGISTRY=1``, or
  explicitly) so an unarmed test process can never latch one.

Counters/gauges export through the shared registry (``compile.*`` in
``/v1/metrics`` + Prometheus); :func:`snapshot` is the flight
recorder's ``executables.json`` section and the ``memreport`` CLI's
compile table.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from tpuflow.obs.gauges import inc_counter, set_gauge

_LOCK = threading.Lock()
_SITES: Dict[str, Dict[str, Any]] = {}
_ENABLED = bool(os.environ.get("TPUFLOW_COMPILE_REGISTRY"))
#: 'off' = count compiles only; 'lower' = also retrace once per compile
#: event for pre-compile cost analysis (AOT registrations always carry
#: full analysis — they never pay anything extra)
_ANALYZE = "lower" if os.environ.get("TPUFLOW_COMPILE_ANALYSIS") else "off"
_THRESHOLD = int(os.environ.get("TPUFLOW_RECOMPILE_THRESHOLD", "16"))
_WATCHDOG = None  # None -> health.default_watchdog() at trip time
_MAX_SIGS = 6  # recent shape signatures kept per site


def enable(analyze: Optional[str] = None) -> None:
    """Arm the registry (idempotent). ``analyze='lower'`` opts into
    per-compile cost analysis on plain jit sites (one retrace per
    compile event — compile-dominated test suites leave it off)."""
    global _ENABLED
    if analyze is not None:
        configure(analyze=analyze)
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def is_enabled() -> bool:
    return _ENABLED


def configure(threshold: Optional[int] = None, watchdog=None,
              analyze: Optional[str] = None) -> None:
    """Adjust the recompile-trip threshold / trip surface / analysis
    mode (tests inject a private Watchdog and a tiny threshold)."""
    global _THRESHOLD, _WATCHDOG, _ANALYZE
    if threshold is not None:
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        _THRESHOLD = int(threshold)
    if watchdog is not None:
        _WATCHDOG = watchdog
    if analyze is not None:
        if analyze not in ("off", "lower"):
            raise ValueError(
                f"analyze must be 'off' or 'lower', got {analyze!r}"
            )
        _ANALYZE = analyze


def clear() -> None:
    """Drop every site record (test isolation). Does not disarm."""
    with _LOCK:
        _SITES.clear()


def _site(key: str) -> Dict[str, Any]:
    # callers hold _LOCK
    s = _SITES.get(key)
    if s is None:
        s = _SITES[key] = {
            "key": key, "kind": "jit", "compiles": 0, "calls": 0,
            "wall_s_total": 0.0, "last_wall_s": 0.0,
            "shapes": [], "cost": None, "memory": None, "tripped": False,
        }
    return s


def shape_signature(args: tuple, kwargs: Optional[dict] = None,
                    limit: int = 16) -> str:
    """Compact ``dtype[shape]`` signature of a call's array arguments —
    what the recompile watchdog quotes so a trip names the offending
    shapes, not just a count."""
    import jax

    parts: List[str] = []
    for leaf in jax.tree_util.tree_leaves((args, kwargs or {})):
        sh = getattr(leaf, "shape", None)
        if sh is not None:
            dt = getattr(getattr(leaf, "dtype", None), "name", "?")
            parts.append(f"{dt}[{','.join(str(d) for d in sh)}]")
        else:
            parts.append(type(leaf).__name__)
        if len(parts) >= limit:
            parts.append("...")
            break
    return "(" + ", ".join(parts) + ")"


def record_compile(key: str, wall_s: float = 0.0,
                   sig: Optional[str] = None, kind: str = "jit",
                   cost: Optional[Dict[str, Any]] = None,
                   memory: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Record one compile event under ``key`` (every path — jit-miss
    detection, AOT registration — funnels here). No-op while the
    registry is DISARMED, like the span tracer: counts always mean
    "since arming", so arming a long-lived process mid-flight cannot
    inherit a history it never observed.

    The watchdog trips on DISTINCT SHAPE SIGNATURES per site crossing
    the threshold, not raw compile counts: bucket-menu explosion and
    shape leaks grow the distinct-shape set, while N separate fits
    re-AOT-compiling the same step at the SAME shapes is deliberate
    work (same-shape cache thrash is the _LRU eviction counter's
    signal instead)."""
    if not _ENABLED:
        return {}
    with _LOCK:
        s = _site(key)
        s["compiles"] += 1
        s["wall_s_total"] += float(wall_s)
        s["last_wall_s"] = float(wall_s)
        if kind == "aot":
            s["kind"] = "aot"
        sigset = s.setdefault("_sigset", set())
        if sig:
            if len(sigset) <= _THRESHOLD + 8:  # bounded bookkeeping
                sigset.add(sig)
            if not s["shapes"] or s["shapes"][-1] != sig:
                s["shapes"].append(sig)
                del s["shapes"][:-_MAX_SIGS]
        if cost is not None:
            s["cost"] = cost
        if memory is not None:
            s["memory"] = memory
        n = s["compiles"]
        distinct = len(sigset)
        trip = (distinct > _THRESHOLD and not s["tripped"])
        if trip:
            s["tripped"] = True
        shapes = list(s["shapes"])
        set_gauge("compile.sites", float(len(_SITES)))
    inc_counter("compile.compiles_total")
    if n > 1:
        inc_counter("compile.recompiles_total")
    if trip:
        inc_counter("compile.recompile_trips_total")
        wd = _WATCHDOG
        if wd is None:
            from tpuflow.obs.health import default_watchdog

            wd = default_watchdog()
        wd.trip(
            f"recompile storm: {key} compiled {n}x across {distinct} "
            f"distinct shapes (threshold {_THRESHOLD}); recent shapes: "
            f"{'; '.join(shapes) or '?'}",
            kind="recompile", site=key, compiles=n,
            distinct_shapes=distinct,
            threshold=_THRESHOLD, shapes=shapes,
        )
    with _LOCK:
        return _snapshot_site(_SITES[key])


def _snapshot_site(s: Dict[str, Any]) -> Dict[str, Any]:
    # callers hold _LOCK; JSON-able copy (the _sigset working set
    # collapses to its count)
    out = {k: v for k, v in s.items() if not k.startswith("_")}
    out["distinct_shapes"] = len(s.get("_sigset", ()))
    return out


def register_compiled(key: str, compiled, wall_s: float = 0.0,
                      sig: Optional[str] = None):
    """Register an already-compiled executable (AOT sites): full XLA
    cost analysis (FLOPs + bytes accessed, summed across device
    shares), arithmetic intensity + roofline verdict, and
    ``memory_analysis`` (temp/argument/output/alias bytes). Returns
    ``compiled`` so the call site stays one expression. A no-op
    passthrough while the registry is disarmed — the analyses would
    be discarded anyway (callers that want FLOPs regardless read
    ``cost_analysis_of`` themselves; see :func:`site_cost`)."""
    if not _ENABLED:
        return compiled
    from tpuflow.obs.mfu import cost_analysis_of, roofline

    cost = cost_analysis_of(compiled)
    if cost.get("flops") and cost.get("bytes_accessed"):
        cost.update(roofline(cost["flops"], cost["bytes_accessed"]))
    mem = None
    try:
        ma = compiled.memory_analysis()
        mem = {
            "temp_bytes": int(ma.temp_size_in_bytes),
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "generated_code_bytes": int(ma.generated_code_size_in_bytes),
        }
    except Exception:
        pass  # backend without memory analysis: the cost half stands
    record_compile(key, wall_s=wall_s, sig=sig, kind="aot",
                   cost=cost or None, memory=mem)
    return compiled


class RegisteredJit:
    """``jax.jit`` with a registry conscience.

    Disabled (default): ``__call__`` is one module-flag read plus
    delegation to the underlying jitted callable — the tier-1 overhead
    guard pins this path. Enabled: each call reads the jit dispatch
    cache size (a C call); growth is a compile event (jax's dispatch
    cache is keyed exactly like its compiles, so the delta is the
    truth, not a heuristic). ``aot_compile`` is the full-analysis
    path for sites that want the compiled object anyway."""

    __slots__ = ("key", "_jit", "_csize", "_seen")

    def __init__(self, fn: Callable, key: str, **jit_kwargs: Any):
        import jax

        self.key = key
        self._jit = jax.jit(fn, **jit_kwargs)
        self._csize = getattr(self._jit, "_cache_size", None)
        self._seen = 0

    def __call__(self, *args: Any, **kwargs: Any):
        if not _ENABLED:
            return self._jit(*args, **kwargs)
        t0 = time.perf_counter()
        out = self._jit(*args, **kwargs)
        with _LOCK:
            _site(self.key)["calls"] += 1
        if self._csize is not None:
            try:
                n = self._csize()
            except Exception:  # pragma: no cover - C-API drift
                n = self._seen
            if n > self._seen:
                self._seen = n
                self._on_miss(time.perf_counter() - t0, args, kwargs)
        return out

    def _on_miss(self, wall_s: float, args: tuple, kwargs: dict) -> None:
        sig = None
        cost = None
        try:
            sig = shape_signature(args, kwargs)
            if _ANALYZE == "lower":
                from tpuflow.obs.mfu import cost_analysis_of, roofline

                lowered = self._jit.lower(*args, **kwargs)
                cost = cost_analysis_of(lowered)
                if cost.get("flops") and cost.get("bytes_accessed"):
                    cost.update(roofline(cost["flops"],
                                         cost["bytes_accessed"]))
        except Exception:
            pass  # observing a compile must never fail the dispatch
        record_compile(self.key, wall_s=wall_s, sig=sig,
                       cost=cost or None)

    def lower(self, *args: Any, **kwargs: Any):
        return self._jit.lower(*args, **kwargs)

    def eval_shape(self, *args: Any, **kwargs: Any):
        return self._jit.eval_shape(*args, **kwargs)

    def aot_compile(self, *args: Any, **kwargs: Any):
        """``lower().compile()`` + registration in one step — what the
        trainers' existing AOT-for-FLOPs sites route through, so the
        registry's deepest records cost nothing extra."""
        t0 = time.perf_counter()
        compiled = self._jit.lower(*args, **kwargs).compile()
        return register_compiled(
            self.key, compiled, wall_s=time.perf_counter() - t0,
            sig=shape_signature(args, kwargs),
        )


def registered_jit(fn: Optional[Callable] = None, *,
                   key: Optional[str] = None, **jit_kwargs: Any):
    """Drop-in for ``jax.jit`` that registers its compiles. Usable as
    ``registered_jit(fn, key=..., donate_argnums=0)`` or as a
    decorator ``@registered_jit(key=...)``."""
    if fn is None:
        def wrap(f: Callable) -> RegisteredJit:
            return RegisteredJit(
                f, key or getattr(f, "__qualname__", "anon"), **jit_kwargs
            )

        return wrap
    return RegisteredJit(
        fn, key or getattr(fn, "__qualname__", "anon"), **jit_kwargs
    )


def site_cost(key: str) -> Optional[Dict[str, Any]]:
    """The cost-analysis dict an AOT registration already captured for
    ``key`` (None when disarmed / never registered / analysis failed)
    — so a call site that registered an executable one line ago does
    not re-run XLA's analysis (or double-count its error counter) to
    read the same numbers."""
    with _LOCK:
        s = _SITES.get(key)
        cost = s.get("cost") if s else None
        return dict(cost) if cost else None


def snapshot() -> Dict[str, Any]:
    """JSON-able registry state — the flight recorder's
    ``executables.json`` section and the memreport compile table.
    Includes the infer compile-cache (hit/miss/eviction) stats so one
    section answers both "what compiled" and "what is cached"."""
    with _LOCK:
        sites = {k: _snapshot_site(v) for k, v in _SITES.items()}
    caches: Dict[str, Any] = {}
    try:
        from tpuflow.infer.generate import compile_cache_stats

        caches = compile_cache_stats()
    except Exception:
        pass
    return {
        "enabled": _ENABLED,
        "analyze": _ANALYZE,
        "recompile_threshold": _THRESHOLD,
        "compiles_total": sum(s["compiles"] for s in sites.values()),
        "sites": sites,
        "caches": caches,
    }
