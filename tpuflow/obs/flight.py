"""Flight recorder (ISSUE 5 tentpole): automatic post-mortems.

The paper's L6 layer makes a run inspectable AFTER the fact only if
someone was logging the right thing before it died. The flight
recorder closes that gap: when a watchdog trips, an unhandled
exception escapes, or a SIGTERM lands, :func:`dump` ATOMICALLY writes
one post-mortem bundle directory holding everything the live plane
knew at that moment:

- ``manifest.json`` — reason, wall time, pid, watchdog state, caller
  context, and any :func:`annotate` notes (e.g. a serving drain);
- ``spans.json`` — the span ring as a Chrome trace export (what the
  process was doing in the seconds before the trip; present when the
  tracer is enabled);
- ``gauges.json`` — the full gauge/counter/histogram snapshot
  (windowed + ``_cum``);
- ``timeseries.json`` — the snapshot ring export, when one is ticking
  (how the numbers MOVED leading up to the trip);
- ``sysmetrics.json`` — host CPU/mem + device HBM;
- ``memory.json`` — the device-buffer ledger's attribution + timeline
  (ISSUE 7; present when anything was tagged);
- ``executables.json`` — the compile/executable registry snapshot
  (sites, cost/memory analyses, compile-cache stats);
- one ``<provider>.json`` per registered provider — e.g. the serving
  scheduler's in-flight request states.

Atomicity is the directory-rename idiom (stage into ``<dir>.tmp-pid``,
``os.replace`` into place): a reader never sees a torn bundle, and a
crash mid-dump leaves only a ``.tmp-`` turd. Read bundles back with
:func:`load` / ``python -m tpuflow.cli.obs postmortem <dir>``.

Arming is explicit: :func:`install` hooks ``sys.excepthook`` (and
optionally SIGTERM, chaining any previous handler — the preemption
machinery in train/preempt.py installs its own and must keep working);
watchdog-trip dumps are wired by handing :func:`trip_dumper` to a
:class:`~tpuflow.obs.health.Watchdog`. Nothing is hooked by default.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

_LOCK = threading.Lock()
_PROVIDERS: Dict[str, Callable[[], Any]] = {}
_NOTES: Dict[str, Any] = {}
_SEQ = 0

_BUNDLE_FILES = ("manifest.json", "gauges.json", "sysmetrics.json")


def add_provider(name: str, fn: Callable[[], Any]) -> None:
    """Register ``fn`` (→ JSON-able) to be captured into
    ``<name>.json`` in every future bundle. Last registration per name
    wins; a raising provider is recorded as its error, never aborts
    the dump."""
    with _LOCK:
        _PROVIDERS[name] = fn


def remove_provider(name: str) -> None:
    with _LOCK:
        _PROVIDERS.pop(name, None)


def annotate(key: str, value: Any) -> None:
    """Pin a JSON-able note onto every FUTURE bundle's manifest
    (``manifest["notes"][key]``) — for process-lifecycle facts a
    provider snapshot cannot carry because they happened as an EVENT
    (e.g. a serving drain: the post-mortem of a SIGTERM'd server must
    say the truncation-free drain ran, not just show empty queues).
    Last value per key wins; ``annotate(key, None)`` removes."""
    with _LOCK:
        if value is None:
            _NOTES.pop(key, None)
        else:
            _NOTES[key] = value


def append_note(key: str, value: Any, cap: int = 16) -> None:
    """Append ``value`` to a BOUNDED list note on every future
    bundle's manifest (``manifest["notes"][key]`` is the most recent
    ``cap`` entries, oldest first) — for lifecycle facts that happen
    repeatedly and whose HISTORY matters: e.g. deployments (ISSUE
    15), where a post-mortem must show which model version was live
    when, not just the latest. :func:`annotate` stays last-write-wins
    for singular facts."""
    with _LOCK:
        cur = _NOTES.get(key)
        if not isinstance(cur, list):
            cur = [] if cur is None else [cur]
        cur.append(value)
        _NOTES[key] = cur[-max(1, int(cap)):]


def _write_json(path: str, obj: Any) -> None:
    with open(path, "w") as f:
        json.dump(obj, f, indent=1, default=str)


def dump(out_dir: str, reason: str,
         context: Optional[Dict[str, Any]] = None) -> str:
    """Write one post-mortem bundle under ``out_dir`` (a NEW
    subdirectory per dump — ``postmortem-<epochsecs>-<seq>``); returns
    its path. Never raises: best-effort capture of every section, with
    per-section errors recorded in the manifest."""
    global _SEQ
    with _LOCK:
        _SEQ += 1
        seq = _SEQ
        providers = dict(_PROVIDERS)
        notes = dict(_NOTES)
    name = f"postmortem-{int(time.time())}-{os.getpid()}-{seq}"
    final = os.path.join(out_dir, name)
    tmp = final + f".tmp-{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    errors: Dict[str, str] = {}
    sections: List[str] = []

    def section(fname: str, fn: Callable[[], Any]) -> None:
        try:
            obj = fn()
        except Exception as e:
            errors[fname] = f"{type(e).__name__}: {e}"
            return
        if obj is None:
            return
        try:
            _write_json(os.path.join(tmp, fname), obj)
            sections.append(fname)
        except Exception as e:  # pragma: no cover - disk-full class
            errors[fname] = f"{type(e).__name__}: {e}"

    from tpuflow.obs import trace
    from tpuflow.obs.gauges import snapshot_gauges

    def spans():
        if not trace.snapshot():
            return None
        # reuse the one chrome exporter (atomic on its own file), then
        # fold the file into the staged bundle
        p = os.path.join(tmp, "spans.json")
        trace.export_chrome_trace(p)
        sections.append("spans.json")
        return None

    section("_spans", spans)
    section("gauges.json", lambda: snapshot_gauges())

    def ts():
        from tpuflow.obs import timeseries

        ring = timeseries.default_ring()
        return ring.export() if ring is not None else None

    section("timeseries.json", ts)

    def sysm():
        from tpuflow.obs.sysmetrics import sample_system_metrics

        return sample_system_metrics()

    section("sysmetrics.json", sysm)

    def memsec():
        from tpuflow.obs import memory

        return memory.snapshot()  # None when nothing was tagged

    section("memory.json", memsec)

    def exsec():
        from tpuflow.obs import executables

        snap = executables.snapshot()
        return snap if (snap["sites"] or snap["caches"]) else None

    section("executables.json", exsec)
    for pname, fn in providers.items():
        section(f"{pname}.json", fn)

    from tpuflow.obs.health import default_watchdog, heartbeat_ages

    manifest = {
        "reason": reason,
        "ts": time.time(),
        "pid": os.getpid(),
        "context": context or {},
        "watchdog": default_watchdog().state(),
        "heartbeat_ages_s": {
            k: round(v, 3) for k, v in heartbeat_ages().items()
        },
        "tracer_enabled": trace.is_enabled(),
        "notes": notes,
        "sections": sorted(sections),
        "errors": errors,
    }
    _write_json(os.path.join(tmp, "manifest.json"), manifest)
    os.replace(tmp, final)  # atomic: a bundle either exists whole or not
    return final


def trip_dumper(out_dir: str) -> Callable[[Dict[str, Any]], None]:
    """A ``Watchdog.on_trip`` callback that dumps into ``out_dir`` —
    the standard wiring: ``watchdog.on_trip.append(flight.
    trip_dumper(dir))``."""

    def on_trip(rec: Dict[str, Any]) -> None:
        dump(out_dir, rec.get("reason", "watchdog trip"), context=rec)

    # records the target dir on the hook (introspection); the
    # trainer-side fit-to-fit dedupe tags its own hooks separately
    # (_trainer_flight, tpuflow.obs.health.monitor_from_config)
    on_trip._flight_dir = out_dir
    return on_trip


# ---- global hooks (explicitly armed) --------------------------------

_INSTALLED: Dict[str, Any] = {}


def install(out_dir: str, signals: bool = False) -> None:
    """Arm process-level capture into ``out_dir``: ``sys.excepthook``
    (unhandled exception → bundle, then the previous hook runs) and,
    with ``signals=True`` on the main thread, SIGTERM (bundle, then
    the PREVIOUS handler — the trainers' preemption flag keeps
    working; default action re-raised when there was none).
    Idempotent; :func:`uninstall` restores."""
    import sys

    with _LOCK:
        already = "dir" in _INSTALLED
        _INSTALLED["dir"] = out_dir
    if not already:
        prev_hook = sys.excepthook

        def hook(etype, evalue, tb):
            try:
                # read the CURRENT dir: a re-install may have moved it
                dump(_INSTALLED.get("dir", out_dir),
                     f"unhandled {etype.__name__}: {evalue}")
            except Exception:
                pass
            prev_hook(etype, evalue, tb)

        _INSTALLED["excepthook_prev"] = prev_hook
        sys.excepthook = hook
    # signals arm independently of the excepthook, so a re-install
    # that newly asks for them still gets them
    if signals and "sigterm_prev" not in _INSTALLED:
        import signal

        if threading.current_thread() is threading.main_thread():
            def on_term(signum, frame):
                try:
                    dump(_INSTALLED.get("dir", out_dir), "SIGTERM")
                except Exception:
                    pass
                prev = _INSTALLED.get("sigterm_prev")
                if callable(prev):
                    prev(signum, frame)
                elif prev == signal.SIG_DFL:
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            _INSTALLED["sigterm_prev"] = signal.signal(
                signal.SIGTERM, on_term
            )


def uninstall() -> None:
    import sys

    with _LOCK:
        if "dir" not in _INSTALLED:
            return
        prev_hook = _INSTALLED.pop("excepthook_prev", None)
        sig_prev = _INSTALLED.pop("sigterm_prev", "-none-")
        _INSTALLED.pop("dir", None)
    if prev_hook is not None:
        sys.excepthook = prev_hook
    if sig_prev != "-none-":
        import signal

        if threading.current_thread() is threading.main_thread():
            signal.signal(signal.SIGTERM, sig_prev)


# ---- read side ------------------------------------------------------

def list_bundles(out_dir: str) -> List[str]:
    """Bundle subdirectories under ``out_dir``, oldest first."""
    if not os.path.isdir(out_dir):
        return []
    out = []
    for d in sorted(os.listdir(out_dir)):
        p = os.path.join(out_dir, d)
        if (d.startswith("postmortem-") and ".tmp-" not in d
                and os.path.isfile(os.path.join(p, "manifest.json"))):
            out.append(p)
    return out


def load(bundle_dir: str) -> Dict[str, Any]:
    """Parse a bundle (or the NEWEST bundle inside a dump root) into
    ``{section_name: parsed_json}``; raises FileNotFoundError when
    there is no manifest to anchor on."""
    if not os.path.isfile(os.path.join(bundle_dir, "manifest.json")):
        inner = list_bundles(bundle_dir)
        if not inner:
            raise FileNotFoundError(
                f"no flight-record bundle under {bundle_dir}"
            )
        bundle_dir = inner[-1]
    out: Dict[str, Any] = {"_path": bundle_dir}
    for fn in sorted(os.listdir(bundle_dir)):
        if fn.endswith(".json"):
            with open(os.path.join(bundle_dir, fn)) as f:
                out[fn[:-5]] = json.load(f)
    return out


def format_postmortem(bundle: Dict[str, Any], top_spans: int = 12,
                      top_gauges: int = 20) -> str:
    """Human post-mortem: reason, watchdog trips, heartbeat ages, the
    LAST spans before the dump (what the process was doing), the top
    gauges, and any in-flight serve requests."""
    man = bundle.get("manifest", {})
    lines = [
        f"flight record: {bundle.get('_path', '?')}",
        f"  reason : {man.get('reason', '?')}",
        f"  time   : {time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(man.get('ts', 0)))}"
        f"  (pid {man.get('pid', '?')})",
    ]
    wd = man.get("watchdog", {})
    if wd.get("trips"):
        lines.append("  watchdog trips:")
        for t in wd["trips"][-5:]:
            lines.append(f"    - {t.get('reason')}")
    hbs = man.get("heartbeat_ages_s", {})
    if hbs:
        lines.append("  heartbeat ages (s): " + ", ".join(
            f"{k}={v:g}" for k, v in sorted(hbs.items())
        ))
    if man.get("errors"):
        lines.append(f"  capture errors: {man['errors']}")
    spans = bundle.get("spans", {}).get("traceEvents", [])
    xs = [e for e in spans if e.get("ph") == "X"]
    if xs:
        xs.sort(key=lambda e: e.get("ts", 0) + e.get("dur", 0))
        lines.append(f"  last {min(top_spans, len(xs))} spans before "
                     "the dump:")
        for e in xs[-top_spans:]:
            lines.append(
                f"    {e['name']:<28} {e.get('dur', 0) / 1e3:>10.3f} ms"
                f"  [{e.get('args', {}).get('trace_id', '')}]"
            )
    gauges = bundle.get("gauges", {})
    if gauges:
        lines.append("  gauges (subset):")
        for k in sorted(gauges)[:top_gauges]:
            lines.append(f"    {k} = {gauges[k]}")
        if len(gauges) > top_gauges:
            lines.append(f"    ... {len(gauges) - top_gauges} more")
    for key in sorted(bundle):
        # any scheduler's provider section, whatever its gauge prefix
        # ("serve_requests", "serve.b_requests", ...)
        if not key.endswith("_requests"):
            continue
        reqs = bundle[key]
        if not reqs:
            continue
        lines.append(f"  in-flight requests [{key}] ({len(reqs)}):")
        for r in reqs[:10]:
            lines.append(
                f"    {r.get('id', '?'):<14} state={r.get('state', '?')}"
                f" tokens={r.get('n_tokens', 0)}"
            )
    return "\n".join(lines)
