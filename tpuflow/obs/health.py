"""Health watchdogs (ISSUE 5 tentpole): non-finite guard, loss-spike
detector, stall detector — the automatic half of observability.

Goyal et al. (*Accurate, Large Minibatch SGD*, 2017) motivates the
loss half: large-batch LR scaling is exactly the regime where a run
diverges silently, and every unwatched step after the first NaN is a
wasted chip-hour. The serving half is the wedged-scheduler problem: a
thread that stops making progress keeps passing a liveness check
forever. Three detectors, one :class:`Watchdog` trip surface:

- **non-finite guard** — trainers (``TrainConfig.watchdog=True``) roll
  a device-side ``isfinite(loss) & isfinite(grad_norm)`` flag into the
  SAME metrics block every step already computes, so detection costs
  zero extra host syncs; the still-device-resident block is handed to
  :meth:`HealthMonitor.watch_device`, whose worker THREAD fetches it —
  the training thread never blocks, and a NaN at step i is attributed
  to step i (within-one-step granularity) as soon as the device
  finishes it;
- **EWMA loss-spike detector** (:class:`LossSpikeDetector`) — an
  exponentially-weighted mean + absolute-deviation band; a loss far
  above the band after warmup trips (divergence looks like this long
  before it reaches inf);
- **stall detector** (:class:`StallDetector`) — hot loops stamp
  :func:`heartbeat` (one lock + dict store per DISPATCH, not per op);
  a monitor thread trips when no registered heartbeat advanced within
  ``timeout_s`` (no step / no decode segment completed — the wedge a
  readiness probe must surface).

A trip sets ``health.watchdog_tripped``/``health.trips_total`` gauges,
records the reason + step, fires registered callbacks (the flight
recorder's dump hook — :mod:`tpuflow.obs.flight`), and is visible to
the serve frontend's readiness endpoint. Nothing in this module runs
unless armed; the tier-1 overhead guard pins the disarmed cost.
"""

from __future__ import annotations

import math
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from tpuflow.obs.gauges import inc_counter, set_gauge

# ---- heartbeats -----------------------------------------------------

_HB_LOCK = threading.Lock()
_HEARTBEATS: Dict[str, float] = {}


def heartbeat(name: str, now: Optional[float] = None) -> None:
    """Stamp liveness for ``name`` (monotonic clock). Called once per
    trainer step / serve decode segment — cheap enough to stay
    unconditional in production loops."""
    t = time.monotonic() if now is None else now
    with _HB_LOCK:
        _HEARTBEATS[name] = t


def heartbeat_ts(name: str) -> Optional[float]:
    """Raw monotonic stamp of ``name``'s last beat (None = never) —
    detectors compare this against their own arming anchor so a stamp
    from a PREVIOUS run cannot read as current liveness."""
    with _HB_LOCK:
        return _HEARTBEATS.get(name)


def heartbeat_age(name: str, now: Optional[float] = None
                  ) -> Optional[float]:
    """Seconds since ``name`` last beat (None = never)."""
    t0 = heartbeat_ts(name)
    if t0 is None:
        return None
    return (time.monotonic() if now is None else now) - t0


def heartbeat_ages(prefix: Optional[str] = None,
                   now: Optional[float] = None) -> Dict[str, float]:
    t = time.monotonic() if now is None else now
    with _HB_LOCK:
        items = dict(_HEARTBEATS)
    return {
        k: t - v for k, v in items.items()
        if prefix is None or k.startswith(prefix)
    }


def clear_heartbeats(prefix: Optional[str] = None) -> None:
    with _HB_LOCK:
        if prefix is None:
            _HEARTBEATS.clear()
        else:
            for k in [k for k in _HEARTBEATS if k.startswith(prefix)]:
                del _HEARTBEATS[k]


# ---- trip surface ---------------------------------------------------

class Watchdog:
    """Latched trip state shared by every detector in a process.

    ``trip`` is idempotent-ish (every call records, the FIRST sets the
    latched reason), publishes ``health.*`` gauges, and fires
    ``on_trip`` callbacks OUTSIDE the lock (a flight-recorder dump
    must not deadlock a detector thread). A process-wide default
    instance backs the trainers/serving runtime unless callers inject
    their own."""

    def __init__(self, clock: Callable[[], float] = time.time):
        self._lock = threading.Lock()
        self.clock = clock
        self.tripped = False
        self.reason: Optional[str] = None
        self.trips: List[Dict[str, Any]] = []
        # monotonic, never reset: consumers that only care about trips
        # since their own arming (a new fit on the shared process
        # surface) remember this and compare — no global reset needed
        self.trip_count = 0
        self.on_trip: List[Callable[[Dict[str, Any]], None]] = []

    def trip(self, reason: str, **detail: Any) -> Dict[str, Any]:
        rec = {"reason": reason, "ts": self.clock(), **detail}
        with self._lock:
            first = not self.tripped
            self.tripped = True
            if first:
                self.reason = reason
            self.trips.append(rec)
            self.trip_count += 1
            if len(self.trips) > 64:
                del self.trips[0]
            cbs = list(self.on_trip)
        set_gauge("health.watchdog_tripped", 1.0)
        inc_counter("health.trips_total")
        for cb in cbs:
            try:
                cb(rec)
            except Exception:
                pass  # a broken dump hook must not mask the trip
        return rec

    def reset(self) -> None:
        with self._lock:
            self.tripped = False
            self.reason = None
            self.trips.clear()
        set_gauge("health.watchdog_tripped", 0.0)

    def state(self) -> Dict[str, Any]:
        """JSON-able trip state (readiness endpoints, flight manifest)."""
        with self._lock:
            return {
                "tripped": self.tripped,
                "reason": self.reason,
                "trips": [dict(t) for t in self.trips],
            }


_DEFAULT_WATCHDOG = Watchdog()


def default_watchdog() -> Watchdog:
    return _DEFAULT_WATCHDOG


# ---- detectors ------------------------------------------------------

class LossSpikeDetector:
    """EWMA mean + EWMA absolute-deviation band over a loss series.

    Trips when, after ``warmup`` updates, a value exceeds
    ``mean + factor * dev`` AND ``mean * min_ratio`` (the ratio guard
    keeps a converged flat loss from tripping on deviation noise —
    dev → 0 makes any wiggle a large z-score). Non-finite values are
    NOT this detector's job (the non-finite guard trips first) and are
    skipped so one NaN cannot poison the running statistics."""

    def __init__(self, factor: float = 6.0, alpha: float = 0.05,
                 warmup: int = 20, min_ratio: float = 1.25):
        if not 0 < alpha <= 1:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.factor = float(factor)
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self.min_ratio = float(min_ratio)
        self.mean: Optional[float] = None
        self.dev = 0.0
        self.n = 0

    def update(self, value: float) -> bool:
        """Feed one loss; True = spike (statistics NOT updated with
        the spiking value, so a plateau at the spike level keeps
        tripping rather than normalizing it)."""
        v = float(value)
        if not math.isfinite(v):
            return False
        if self.mean is None:
            self.mean = v
            self.n = 1
            return False
        spiking = (
            self.n >= self.warmup
            and v > self.mean + self.factor * self.dev
            and v > self.mean * self.min_ratio
        )
        if not spiking:
            a = self.alpha
            self.dev = (1 - a) * self.dev + a * abs(v - self.mean)
            self.mean = (1 - a) * self.mean + a * v
            self.n += 1
        return spiking


class StallDetector:
    """Trips when a registered heartbeat stops advancing.

    ``check(now)`` is the synchronous decision (unit-testable with an
    injectable clock); :meth:`start` runs it on a poll thread.

    Staleness is anchored, never absolute — heartbeats are
    process-global and outlive the run that stamped them, so raw age
    would misfire in exactly the healthy cases:

    - stamps from BEFORE this detector was armed are ignored (a
      previous fit's ``train.step`` beat is history, not liveness);
    - an ``active``-gated name re-anchors on every idle→busy
      transition (a serving scheduler that sat idle for 5 minutes has
      an arbitrarily old segment stamp the moment traffic resumes —
      the stall clock must start at the transition, not at the last
      pre-idle segment);
    - a name that has never beat *since its anchor* trips only when
      it beat earlier within this arming (it proved the loop reaches
      it) or was registered ``require=True`` — a run that has not
      reached that loop yet is not stalled."""

    def __init__(self, timeout_s: float,
                 watchdog: Optional[Watchdog] = None,
                 clock: Callable[[], float] = time.monotonic):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.watchdog = watchdog or default_watchdog()
        self.clock = clock
        self._names: Dict[str, tuple] = {}
        self._armed_at = self.clock()
        self._anchor: Dict[str, float] = {}
        self._idle: Dict[str, bool] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def watch(self, name: str, require: bool = False,
              active: Optional[Callable[[], bool]] = None
              ) -> "StallDetector":
        """Watch ``name``. ``active`` gates the check: when it returns
        False the name is skipped and the stall clock re-anchors when
        it next returns True — e.g. an idle serving scheduler
        legitimately stops decoding, so its segment heartbeat only
        counts while work is pending (``active=lambda: not
        sched.idle()``)."""
        self._names[name] = (require, active)
        return self

    def check(self, now: Optional[float] = None) -> Optional[str]:
        """The stalled name (and a watchdog trip), or None."""
        t = self.clock() if now is None else now
        for name, (require, active) in self._names.items():
            if active is not None:
                if not active():
                    self._idle[name] = True
                    continue
                if self._idle.get(name, True):
                    # idle→busy (or first look): the stall clock
                    # starts NOW, not at the last pre-idle beat
                    self._anchor[name] = t
                    self._idle[name] = False
            anchor = self._anchor.get(name, self._armed_at)
            ts = heartbeat_ts(name)
            if ts is not None and ts >= anchor:
                age = t - ts
            elif require or (ts is not None and ts >= self._armed_at):
                # no beat since the anchor, but the name is required
                # or beat earlier within THIS arming (so the loop
                # provably reaches it): silence since the anchor is
                # the signal. A stamp from BEFORE arming is a previous
                # run's history and counts as never-beat.
                age = t - anchor
            else:
                continue  # never beat: the run hasn't reached it yet
            if age > self.timeout_s:
                self.watchdog.trip(
                    f"stall: no {name} heartbeat in {age:.1f}s "
                    f"(timeout {self.timeout_s:g}s)",
                    kind="stall", heartbeat=name, age_s=round(age, 3),
                )
                return name
        return None

    def start(self, poll_s: Optional[float] = None) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        poll = poll_s if poll_s is not None else max(
            0.25, self.timeout_s / 4
        )

        def loop():
            while not self._stop.wait(poll):
                if self.check() is not None:
                    return  # latched — one trip is the signal

        self._thread = threading.Thread(
            target=loop, name="tpuflow-stall-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


# ---- the trainer-facing monitor -------------------------------------

class HealthMonitor:
    """Per-run composition of the detectors for a training loop.

    The hot-path contract: :meth:`watch_device` takes the step's
    STILL-DEVICE-RESIDENT metrics block and returns immediately (a
    bounded-queue handoff); the worker thread pays the device fetch,
    runs the non-finite guard and the spike detector, and stamps the
    ``train.step`` heartbeat. If the worker falls behind the queue
    drops the OLDEST block (guarding is best-effort sampling, training
    throughput is not negotiable) and counts the drop.

    Scalar-side (already-fetched) checks go through :meth:`check_host`
    — also what the unit tests drive with an injectable clock.
    """

    HEARTBEAT = "train.step"

    def __init__(
        self,
        watchdog: Optional[Watchdog] = None,
        spike_factor: float = 6.0,
        spike_warmup: int = 20,
        stall_timeout_s: Optional[float] = None,
        queue_cap: int = 64,
        guard_metrics: bool = True,
    ):
        # default to the PROCESS trip surface: flight-record manifests
        # and the serve /readyz gate read default_watchdog(), so a
        # trainer trip must land there, not on a private island (pass
        # an explicit Watchdog for isolation — unit tests do)
        self.watchdog = watchdog or default_watchdog()
        self.spike = LossSpikeDetector(factor=spike_factor,
                                       warmup=spike_warmup)
        # guard_metrics=False: heartbeat-only mode (the stall detector
        # is wanted, the NaN/spike guards are not — TrainConfig's
        # stall_timeout_s without watchdog=True)
        self.guard_metrics = bool(guard_metrics)
        # active-gates the stall watch: the trainers pause() around
        # legitimate non-step phases (epoch-end eval, checkpointing)
        # whose wall time is allowed to exceed stall_timeout_s — the
        # same idle→busy re-anchoring discipline as the serve side
        self._active = True
        self.stall: Optional[StallDetector] = None
        if stall_timeout_s:
            self.stall = StallDetector(stall_timeout_s,
                                       watchdog=self.watchdog)
            self.stall.watch(self.HEARTBEAT,
                             active=lambda: self._active)
            self.stall.start()
        # trips BEFORE this arming belong to other surfaces/runs on
        # the shared process watchdog: .tripped/.trips() see only
        # newer ones, so a serve-side latched trip neither halts a
        # fresh fit at step 0 nor gets erased by it
        self._trip0 = self.watchdog.trip_count
        self.dropped = 0
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_cap)
        # queued + in-flight blocks: drain() must wait for the worker
        # to FINISH the popped item, not just for an empty queue
        self._pending = 0
        self._pending_lock = threading.Lock()
        # import on the CONSTRUCTING thread: a lazy import inside the
        # worker can race another thread's in-progress `import jax`
        # and observe a partially initialized module
        import jax as _jax

        self._jax = _jax
        self._worker = threading.Thread(
            target=self._drain, name="tpuflow-health-monitor",
            daemon=True,
        )
        self._worker.start()

    @property
    def tripped(self) -> bool:
        """True when the watchdog tripped SINCE this monitor armed."""
        return self.watchdog.trip_count > self._trip0

    def trips(self) -> List[Dict[str, Any]]:
        """Trip records from this arming only (see ``_trip0``)."""
        n = self.watchdog.trip_count - self._trip0
        if n <= 0:
            return []
        return self.watchdog.state()["trips"][-n:]

    def acknowledge(self) -> None:
        """Consume the current trip(s) and re-arm (ISSUE 10 recovery):
        after a rollback the fit loop keeps THIS monitor — worker
        thread, stall watch, exporter wiring all stay — but
        ``tripped`` flips back to False by re-anchoring ``_trip0`` at
        the current trip count (the process watchdog's latched state
        is untouched, so flight manifests / /readyz still show the
        history). The spike detector restarts fresh: its EWMA was fed
        by the pre-rollback trajectory, and the replayed steps would
        otherwise be judged against poisoned statistics."""
        self.drain()
        self._trip0 = self.watchdog.trip_count
        self.spike = LossSpikeDetector(factor=self.spike.factor,
                                       alpha=self.spike.alpha,
                                       warmup=self.spike.warmup,
                                       min_ratio=self.spike.min_ratio)

    def pause(self) -> None:
        """Suspend the stall watch (legitimate non-step phase: eval,
        checkpoint). The stall clock re-anchors on :meth:`resume` —
        the pause's duration never reads as silence."""
        self._active = False

    def resume(self) -> None:
        self._active = True

    # ---- hot path (training thread) ---------------------------------
    def watch_device(self, step: int, metrics: Dict[str, Any]) -> None:
        """Hand off a device-resident metrics dict (scalars or
        (k,)-stacked superstep blocks; keys used: ``loss``,
        ``nonfinite``, ``grad_norm``). Never blocks the caller."""
        with self._pending_lock:
            self._pending += 1
        try:
            self._q.put_nowait((step, metrics))
        except queue.Full:
            try:
                self._q.get_nowait()  # drop oldest, keep newest
                self.dropped += 1
                with self._pending_lock:
                    self._pending -= 1
            except queue.Empty:
                pass
            try:
                self._q.put_nowait((step, metrics))
            except queue.Full:
                self.dropped += 1
                with self._pending_lock:
                    self._pending -= 1

    # ---- worker / host side -----------------------------------------
    def _drain(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            step, metrics = item
            try:
                host = self._jax.device_get(metrics)
                self.check_host(step, host)
                heartbeat(self.HEARTBEAT)
            except Exception:
                pass  # donated/deleted buffer during shutdown
            finally:
                with self._pending_lock:
                    self._pending -= 1

    def check_host(self, step: int, metrics: Dict[str, Any]) -> bool:
        """Synchronous guard over HOST values. ``metrics`` values may
        be python floats, 0-d arrays, or (k,) superstep blocks;
        ``step`` is the global index of the block's LAST step (== the
        step itself for scalars), so a bad entry at block index i is
        attributed to ``step - (k - 1) + i`` — within-one-step
        granularity even for fused dispatches. Returns True if a trip
        fired."""
        if not self.guard_metrics:
            return False  # heartbeat-only mode (stall watch without
            # the NaN/spike guards the `watchdog` flag opts into)
        import numpy as np

        losses = np.atleast_1d(
            np.asarray(metrics.get("loss", np.nan), np.float64)
        )
        k = losses.shape[0]
        flags = metrics.get("nonfinite")
        bad = (
            np.atleast_1d(np.asarray(flags, np.float64)) > 0
            if flags is not None else ~np.isfinite(losses)
        )
        gn = metrics.get("grad_norm")
        if gn is not None:
            bad = bad | ~np.isfinite(
                np.atleast_1d(np.asarray(gn, np.float64))
            )
        if bad.any():
            i = int(np.argmax(bad))
            at = step - k + 1 + i
            self.watchdog.trip(
                f"non-finite loss/grad at step {at} "
                f"(loss={losses[min(i, k - 1)]!r})",
                kind="nonfinite", step=at,
            )
            return True
        for i, v in enumerate(losses):
            if self.spike.update(float(v)):
                at = step - k + 1 + i
                self.watchdog.trip(
                    f"loss spike at step {at}: {v:.4g} vs EWMA "
                    f"{self.spike.mean:.4g} (±{self.spike.dev:.4g})",
                    kind="loss_spike", step=at, loss=float(v),
                    ewma=float(self.spike.mean),
                )
                return True
        return False

    def drain(self, timeout: float = 5.0) -> None:
        """Block until queued AND in-flight blocks are fully checked
        (epoch boundaries, tests) — the one place the training thread
        may wait."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._pending_lock:
                if self._pending <= 0:
                    return
            time.sleep(0.005)

    def close(self) -> None:
        self.drain()
        if self.stall is not None:
            self.stall.stop()
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass


def closing(monitor: Optional[HealthMonitor]):
    """Context manager closing ``monitor`` (None accepted) on exit —
    the fit loops ride this inside their existing ``with`` so an
    exception mid-epoch cannot leak the stall thread, which would
    otherwise fire a spurious latched 'stall' trip (and flight dump)
    once the heartbeats stop."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        try:
            yield monitor
        finally:
            if monitor is not None:
                monitor.close()

    return _cm()


def monitor_from_config(cfg) -> Optional[HealthMonitor]:
    """The trainers' one-liner: build a :class:`HealthMonitor` from
    ``TrainConfig``'s plane fields (``watchdog`` / ``stall_timeout_s``
    / ``flight_dir``), start the Prometheus exporter when
    ``metrics_port`` is set, and wire the flight recorder: watchdog
    trips dump into ``flight_dir``, and ``flight.install`` captures
    unhandled exceptions there too (SIGTERM stays the preemption
    machinery's channel during a fit — train/preempt.py owns that
    handler). Returns None when no watchdog is armed — the fit loop's
    per-step cost is then a single ``is not None`` check."""
    port = getattr(cfg, "metrics_port", None)
    if port is not None:
        from tpuflow.obs import prom

        prom.start_exporter(port)
    flight_dir = getattr(cfg, "flight_dir", None)
    if flight_dir:
        from tpuflow.obs import flight

        flight.install(flight_dir)  # unhandled exception -> bundle
    if not (getattr(cfg, "watchdog", False)
            or getattr(cfg, "stall_timeout_s", None)):
        return None
    if getattr(cfg, "watchdog", False):
        # arming the watchdog also arms the compile registry (ISSUE 7):
        # a recompile storm during a watched fit should trip the same
        # surface a NaN does, and the armed per-dispatch cost is one
        # C-level cache-size read. stall_timeout_s ALONE stays
        # heartbeat-only — same contract as guard_metrics below: the
        # user asked for stall detection, not a fit-halting compile
        # guard.
        from tpuflow.obs import executables

        executables.enable()
    # the monitor rides the PROCESS default watchdog (so /readyz and
    # flight manifests see trainer trips) but only reacts to trips
    # NEWER than its own arming — a prior run's latched trip neither
    # halts the new fit at step 0 nor gets erased here
    mon = HealthMonitor(
        stall_timeout_s=getattr(cfg, "stall_timeout_s", None),
        # stall_timeout_s ALONE is heartbeat-only: the NaN/spike
        # guards belong to the `watchdog` flag (config contract)
        guard_metrics=bool(getattr(cfg, "watchdog", False)),
    )
    if flight_dir:
        from tpuflow.obs import flight

        wd = mon.watchdog
        # the watchdog is process-shared: replace the dump hook a
        # PREVIOUS FIT installed instead of stacking duplicates — but
        # only ours (tagged _trainer_flight); a serve frontend's
        # dumper on the same watchdog targets its own directory and
        # must keep firing
        wd.on_trip = [cb for cb in wd.on_trip
                      if not getattr(cb, "_trainer_flight", False)]
        hook = flight.trip_dumper(flight_dir)
        hook._trainer_flight = True
        wd.on_trip.append(hook)
    return mon
