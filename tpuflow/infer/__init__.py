from tpuflow.infer.batch import predict_table  # noqa: F401
