from tpuflow.infer.batch import generate_table, predict_table  # noqa: F401
from tpuflow.infer.generate import clear_compile_cache, generate  # noqa: F401
