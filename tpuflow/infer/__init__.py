from tpuflow.infer.batch import generate_table, predict_table  # noqa: F401
from tpuflow.infer.generate import (  # noqa: F401
    clear_compile_cache,
    compile_cache_stats,
    generate,
    serve_join_fn,
    serve_pool_arrays,
    serve_segment_fn,
    set_compile_cache_size,
)
