from tpuflow.infer.batch import predict_table  # noqa: F401
from tpuflow.infer.generate import generate  # noqa: F401
