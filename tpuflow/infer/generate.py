"""Autoregressive text generation with a KV cache.

The reference has no generative model at all (its inference path is
image classification via a packaged pyfunc, P2/03); this rounds out the
transformer-LM family (tpuflow.models.transformer) with the standard
serving loop, TPU-idiomatically. Two engines share one contract:

- ``engine='blockwise'`` (default): the prompt is fed through the
  decode-mode model in ``ceil(P / prefill_chunk)`` multi-token forward
  passes that populate the KV cache at ``cache_index`` — matmul-shaped
  prefill on the MXU instead of P sequential matvecs — and only the
  ``max_new_tokens`` sampling steps run as single-token scan steps.
  The decode scan itself is chunked into ``decode_segment``-step
  segments under a ``lax.while_loop`` with an all-rows-done check
  between segments, so a batch that emits EOS early stops paying for
  dead steps (bounded by GENERATED length, not total length).
- ``engine='stepwise'``: the original reference loop — ONE jitted
  ``lax.scan`` of ``P + max_new_tokens - 1`` single-token steps covers
  prefill AND sampling. Kept as the parity oracle (the blockwise
  engine is token-identical to it; tests/test_generate.py pins this)
  and as the conservative fallback.

Shared mechanics:

- the KV cache is a flax ``cache`` collection created at trace time
  with the full target length (chunks ``dynamic_update_slice`` into it
  at ``cache_index``), so XLA sees one fixed buffer per layer — no
  growing tensors, no host round-trips per token;
- sampling is temperature + optional top-k and nucleus (top-p)
  filtering over float32 logits, with a per-ROW key derived from
  (seed, logical step, row index) — a row's RNG stream is independent
  of batch shape AND of bucket padding (``pad_lens``, below);
- ``pad_lens`` (blockwise only) marks per-row LEFT padding for
  bucketed serving (tpuflow.packaging.lm buckets prompt lengths to
  powers of two): pad slots are masked out of attention, rotary
  positions and sampling steps are logical (pad-free), so a padded row
  generates the same tokens as its unpadded run.

Greedy (temperature=0) decode is exact argmax; the cache-consistency
property (stepwise logits == full-forward logits) and the
blockwise==stepwise parity are tested in tests/test_generate.py.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax


def _sample(logits, rng, temperature: float, top_k: Optional[int],
            top_p: Optional[float] = None, step=None):
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    want_p = top_p is not None and top_p < 1.0
    if top_k is not None or want_p:
        # ONE descending sort serves both filters, and the keep mask is
        # scattered back by INDEX — a value threshold would keep every
        # token tied with the cutoff logit (uniform logits + top_p=0.5
        # would filter nothing)
        vocab = logits.shape[-1]
        idx = jnp.argsort(logits, axis=-1)[..., ::-1]
        desc = jnp.take_along_axis(logits, idx, axis=-1)
        keep_sorted = jnp.ones(desc.shape, bool)
        if top_k is not None:
            k = min(max(int(top_k), 1), vocab)
            keep_sorted &= jnp.arange(vocab) < k
        if want_p:
            # nucleus: the smallest prefix of descending-prob tokens
            # whose mass reaches top_p (the top token always stays —
            # its preceding cumulative mass is 0)
            probs = jax.nn.softmax(desc, axis=-1)
            before = jnp.cumsum(probs, axis=-1) - probs
            keep_sorted &= before < top_p
        keep = jnp.zeros(desc.shape, bool)
        keep = jnp.put_along_axis(keep, idx, keep_sorted, axis=-1,
                                  inplace=False)
        logits = jnp.where(keep, logits, -1e30)
    # per-ROW keys (fold_in by row index): row i's RANDOMNESS depends
    # only on (seed, step, i), never on the batch SHAPE — so a prompt's
    # sampled continuation no longer varies with pad-row count through
    # the RNG (packaging/lm.py pads length-buckets with copies of row
    # 0; a single batch-shaped categorical draw would give different
    # outputs for the same prompt+seed depending on the pad count).
    # ``step`` (scalar or per-row (B,)) folds the step index here too:
    # the blockwise engine passes the LOGICAL (pad-free) step so a
    # left-padded row draws the same stream as its unpadded run; the
    # stepwise engine pre-folds the step into ``rng`` (equivalent key
    # derivation — fold_in(fold_in(rng, t), i) either way).
    # Caveat: the LOGITS themselves are only batch-shape-invariant up
    # to the backend's reduction order — an ulp-level logit difference
    # near a probability boundary can still flip a draw on some
    # backends; the guarantee here is RNG invariance, not bitwise
    # forward-pass invariance
    rows = jnp.arange(logits.shape[0])
    if step is None:
        keys = jax.vmap(lambda i: jax.random.fold_in(rng, i))(rows)
    else:
        steps = jnp.broadcast_to(
            jnp.asarray(step, jnp.int32), rows.shape
        )
        keys = jax.vmap(
            lambda s, i: jax.random.fold_in(jax.random.fold_in(rng, s), i)
        )(steps, rows)
    return jax.vmap(
        lambda lg, k: jax.random.categorical(k, lg)
    )(logits, keys).astype(jnp.int32)


def generate(
    model,
    params,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    seed: int = 0,
    eos_id: Optional[int] = None,
    pad_lens=None,
    prefill_chunk: Optional[int] = None,
    decode_segment: int = 32,
    engine: str = "blockwise",
) -> jnp.ndarray:
    """Generate continuations for a batch of prompts.

    ``model``: a TransformerLM built with ``decode=False`` (its decode
    twin is derived here via ``.clone(decode=True)``); ``params``: its
    (unboxed) params. ``prompt``: (B, P) int32. Returns (B, P +
    max_new_tokens) int32 — prompts with sampled continuations; after a
    row emits ``eos_id`` its remaining positions repeat ``eos_id``.

    ``engine='blockwise'`` (default) prefills the prompt in
    ``ceil(P / prefill_chunk)`` multi-token forward passes
    (``prefill_chunk=None`` = the whole prompt in one pass; set it to
    bound the chunk's score-matrix VMEM) and then scans ONLY the
    sampling steps, in ``decode_segment``-step segments with an
    all-rows-done early exit between segments (``eos_id`` set). The
    scan trip count is bounded by the GENERATED length.

    ``pad_lens`` (blockwise only): optional (B,) int32 per-row count of
    LEFT pad slots — the bucketed-serving contract
    (tpuflow.packaging.lm). Row r's real prompt occupies positions
    ``pad_lens[r]:P``; pad slots are masked out of attention and the
    row's rotary positions / RNG steps are logical (pad-free), so its
    output tokens (at ``out[r, pad_lens[r]:]``) match the unpadded run.

    ``engine='stepwise'``: the original single-token scan over
    ``P + max_new_tokens - 1`` steps — the parity oracle.
    """
    dm = model.clone(decode=True, seq_axis=None)
    b, p = prompt.shape
    if p < 1:
        raise ValueError("prompt must have at least one token")
    if engine not in ("blockwise", "stepwise"):
        raise ValueError(
            f"engine must be 'blockwise' or 'stepwise', got {engine!r}"
        )
    if top_k is not None:
        vocab = getattr(model, "vocab_size", None)
        if top_k < 1 or (vocab is not None and top_k > vocab):
            raise ValueError(
                f"top_k={top_k} must be in [1, vocab_size"
                f"{'=' + str(vocab) if vocab is not None else ''}]"
            )
    if top_p is not None and not (0.0 < top_p <= 1.0):
        raise ValueError(f"top_p={top_p} must be in (0, 1]")
    prompt = jnp.asarray(prompt, jnp.int32)
    rng = jax.random.key(seed)
    max_len = p + max_new_tokens
    temperature = float(temperature)
    top_k = None if top_k is None else int(top_k)
    top_p = None if top_p is None else float(top_p)

    if pad_lens is not None:
        if engine != "blockwise":
            raise ValueError(
                "pad_lens (bucketed left-padding) requires "
                "engine='blockwise'"
            )
        import numpy as np

        pl = np.asarray(pad_lens, np.int32)
        if pl.shape != (b,):
            raise ValueError(
                f"pad_lens must have shape ({b},), got {pl.shape}"
            )
        if pl.min() < 0 or pl.max() >= p:
            raise ValueError(
                "pad_lens entries must be in [0, P): every row needs "
                "at least one real prompt token"
            )
        pad_lens = jnp.asarray(pl)

    if max_new_tokens < 1:
        return prompt

    if engine == "stepwise":
        run = _compiled_run(dm, b, p, max_len, temperature, top_k, top_p,
                            eos_id)
        return run(params, prompt, rng)

    chunk = p if prefill_chunk is None else max(1, int(prefill_chunk))
    seg = max(1, int(decode_segment))
    run = _compiled_blockwise(
        dm, b, p, max_len, temperature, top_k, top_p, eos_id,
        min(chunk, p), seg, pad_lens is not None,
    )
    if pad_lens is not None:
        return run(params, prompt, rng, pad_lens)
    return run(params, prompt, rng)


def clear_compile_cache() -> None:
    """Drop all memoized jitted decode closures (each holds a compiled
    executable and a model reference). A long-lived server cycling many
    distinct prompt shapes / sampling configs can call this to bound
    resident compile-cache growth; bucketing prompt lengths before
    calling :func:`generate` keeps the cache small in the first place
    (tpuflow.packaging.lm does this for the text surface)."""
    _compiled_run.cache_clear()
    _compiled_blockwise.cache_clear()


def _cache_zeros(dm, b: int, max_len: int):
    """Zero KV cache with the decode model's full-length cache struct,
    via eval_shape (no FLOPs). Built INSIDE the jitted runs so the
    memoized closures hold only ShapeDtypeStructs, not device buffers."""
    cache_shapes = jax.eval_shape(
        lambda: dm.init(
            {"params": jax.random.key(0)},
            jnp.zeros((b, max_len), jnp.int32),
        )["cache"]
    )
    return jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes
    )


@functools.lru_cache(maxsize=64)
def _compiled_blockwise(dm, b: int, p: int, max_len: int,
                        temperature: float, top_k: Optional[int],
                        top_p: Optional[float], eos_id: Optional[int],
                        chunk: int, seg: int, has_pads: bool):
    """The blockwise-prefill + early-exit decode engine, memoized on
    (model, shapes, sampling config, chunking) — a serving loop calling
    generate() per request with identical shapes compiles ONCE (flax
    modules are frozen dataclasses, so ``dm`` is a valid cache key).
    ``pad_lens`` is a RUNTIME argument (``has_pads`` only selects the
    signature), so one bucket shape serves every pad combination.
    Bounded at 64 entries; :func:`clear_compile_cache` empties it."""
    total = max_len - p - 1  # decode steps AFTER the prefill-sampled token

    def _impl(params, prompt, rng, pads):
        cache = _cache_zeros(dm, b, max_len)
        out = jnp.zeros((b, max_len), jnp.int32)
        out = lax.dynamic_update_slice(out, prompt, (0, 0))

        # ---- blockwise prefill: ceil(p/chunk) multi-token passes ----
        # (python loop over STATIC chunk offsets, unrolled at trace
        # time; every pass is an MXU-shaped matmul against the cache)
        logits = None
        for start in range(0, p, chunk):
            width = min(chunk, p - start)
            tok = lax.slice(prompt, (0, start), (b, start + width))
            logits, vars2 = dm.apply(
                {"params": params, "cache": cache}, tok,
                mutable=["cache"], pad_lens=pads,
            )
            cache = vars2["cache"]

        def logical(t):
            # sampling-step index as the row sees it: slot minus pads
            return t - pads if pads is not None else t

        # first generated token: sampled from the LAST prompt
        # position's prefill logits (slot p-1) — no scan step spent
        nxt = _sample(logits[:, -1], rng, temperature, top_k, top_p,
                      step=logical(jnp.int32(p - 1)))
        done = jnp.zeros((b,), jnp.bool_)
        if eos_id is not None:
            done = nxt == eos_id
        out = lax.dynamic_update_slice(out, nxt[:, None], (0, p))

        # ---- early-exit decode: segment scans under a while_loop ----
        def step(carry, t):
            cache, out, done = carry
            tok = lax.dynamic_slice(out, (0, t), (b, 1))
            lg, vars2 = dm.apply(
                {"params": params, "cache": cache}, tok,
                mutable=["cache"], pad_lens=pads,
            )
            nxt = _sample(lg[:, -1], rng, temperature, top_k, top_p,
                          step=logical(t))
            if eos_id is not None:
                nxt = jnp.where(done, jnp.int32(eos_id), nxt)
                done = done | (nxt == eos_id)
            out = lax.dynamic_update_slice(out, nxt[:, None], (0, t + 1))
            return (vars2["cache"], out, done), None

        def run_seg(cache, out, done, t0, n):
            (cache, out, done), _ = lax.scan(
                lambda c, i: step(c, t0 + i), (cache, out, done),
                jnp.arange(n),
            )
            return cache, out, done

        if total > 0:
            seg_n = min(seg, total)
            nfull, rem = divmod(total, seg_n)
            if eos_id is None:
                # no EOS → no early exit possible: one flat scan
                cache, out, done = run_seg(cache, out, done,
                                           jnp.int32(p), total)
            else:
                def cond(c):
                    k, _cache, _out, done = c
                    return (k < nfull) & ~jnp.all(done)

                def body(c):
                    k, cache, out, done = c
                    cache, out, done = run_seg(
                        cache, out, done, p + k * seg_n, seg_n
                    )
                    return (k + 1, cache, out, done)

                _, cache, out, done = lax.while_loop(
                    cond, body, (jnp.int32(0), cache, out, done)
                )
                if rem:
                    cache, out, done = lax.cond(
                        jnp.all(done),
                        lambda c: c,
                        lambda c: run_seg(*c, p + nfull * seg_n, rem),
                        (cache, out, done),
                    )

        if eos_id is not None:
            # early exit leaves post-EOS slots unwritten (zeros); the
            # contract says they repeat eos_id — backfill every slot
            # strictly after a row's first generated EOS (a no-op for
            # slots the scan already filled)
            gen = out[:, p:]
            hit = (gen == eos_id).astype(jnp.int32)
            after = jnp.cumsum(hit, axis=1) - hit
            gen = jnp.where(after > 0, jnp.int32(eos_id), gen)
            out = jnp.concatenate([out[:, :p], gen], axis=1)
        return out

    if has_pads:
        @jax.jit
        def run(params, prompt, rng, pad_lens):
            return _impl(params, prompt, rng, pad_lens)
    else:
        @jax.jit
        def run(params, prompt, rng):
            return _impl(params, prompt, rng, None)

    return run


@functools.lru_cache(maxsize=64)
def _compiled_run(dm, b: int, p: int, max_len: int, temperature: float,
                  top_k: Optional[int], top_p: Optional[float],
                  eos_id: Optional[int]):
    """The stepwise prompt+decode scan (the original engine), memoized
    on (model, shapes, sampling config). ONE scan of ``max_len - 1``
    single-token steps covers prefill and sampling; kept as the parity
    oracle for the blockwise engine and as the conservative fallback."""

    @jax.jit
    def run(params, prompt, rng):
        cache0 = _cache_zeros(dm, b, max_len)
        out0 = jnp.zeros((b, max_len), jnp.int32)
        out0 = lax.dynamic_update_slice(out0, prompt, (0, 0))
        done0 = jnp.zeros((b,), jnp.bool_)

        def step(carry, t):
            cache, out, done = carry
            tok = lax.dynamic_slice(out, (0, t), (b, 1))
            logits, vars2 = dm.apply(
                {"params": params, "cache": cache}, tok, mutable=["cache"]
            )
            nxt = _sample(
                logits[:, -1], jax.random.fold_in(rng, t), temperature,
                top_k, top_p,
            )
            # positions < p-1 are prefill: keep the prompt token that is
            # already in ``out`` instead of the model's prediction
            gen_pos = t + 1 >= p
            cur = lax.dynamic_slice(out, (0, t + 1), (b, 1))[:, 0]
            nxt = jnp.where(gen_pos, nxt, cur)
            if eos_id is not None:  # only GENERATED eos stops a row
                nxt = jnp.where(gen_pos & done, jnp.int32(eos_id), nxt)
                done = done | (gen_pos & (nxt == eos_id))
            out = lax.dynamic_update_slice(out, nxt[:, None], (0, t + 1))
            return (vars2["cache"], out, done), None

        (cache, out, _), _ = lax.scan(
            step, (cache0, out0, done0), jnp.arange(max_len - 1)
        )
        return out

    return run
