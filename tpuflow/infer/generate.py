"""Autoregressive text generation with a KV cache.

The reference has no generative model at all (its inference path is
image classification via a packaged pyfunc, P2/03); this rounds out the
transformer-LM family (tpuflow.models.transformer) with the standard
serving loop, TPU-idiomatically. Two engines share one contract:

- ``engine='blockwise'`` (default): the prompt is fed through the
  decode-mode model in ``ceil(P / prefill_chunk)`` multi-token forward
  passes that populate the KV cache at ``cache_index`` — matmul-shaped
  prefill on the MXU instead of P sequential matvecs — and only the
  ``max_new_tokens`` sampling steps run as single-token scan steps.
  The decode scan itself is chunked into ``decode_segment``-step
  segments under a ``lax.while_loop`` with an all-rows-done check
  between segments, so a batch that emits EOS early stops paying for
  dead steps (bounded by GENERATED length, not total length).
- ``engine='stepwise'``: the original reference loop — ONE jitted
  ``lax.scan`` of ``P + max_new_tokens - 1`` single-token steps covers
  prefill AND sampling. Kept as the parity oracle (the blockwise
  engine is token-identical to it; tests/test_generate.py pins this)
  and as the conservative fallback.

Shared mechanics:

- the KV cache is a flax ``cache`` collection created at trace time
  with the full target length (chunks ``dynamic_update_slice`` into it
  at ``cache_index``), so XLA sees one fixed buffer per layer — no
  growing tensors, no host round-trips per token;
- sampling is temperature + optional top-k and nucleus (top-p)
  filtering over float32 logits, with a per-ROW key derived from
  (seed, logical step, row index) — a row's RNG stream is independent
  of batch shape AND of bucket padding (``pad_lens``, below);
- ``pad_lens`` (blockwise only) marks per-row LEFT padding for
  bucketed serving (tpuflow.packaging.lm buckets prompt lengths to
  powers of two): pad slots are masked out of attention, rotary
  positions and sampling steps are logical (pad-free), so a padded row
  generates the same tokens as its unpadded run.

Greedy (temperature=0) decode is exact argmax; the cache-consistency
property (stepwise logits == full-forward logits) and the
blockwise==stepwise parity are tested in tests/test_generate.py.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from tpuflow.obs import memory as _mem
from tpuflow.obs import trace
# the jit decorator with a compile-registry conscience: every engine
# executable here registers under a stable site key (ISSUE 7), so
# recompile storms — the bucket-menu-explosion failure mode — surface
# in the executable registry and its watchdog instead of only as
# mysterious serving latency
from tpuflow.obs.executables import registered_jit as _rjit


class _LRU:
    """Small LRU memo for compiled decode closures with an EVICTION
    counter — the observable the serving runtime watches.

    ``functools.lru_cache`` bounds growth but hides evictions (its
    ``currsize`` saturates silently); a long-lived server cycling many
    (bucket, slot-shape, sampling) keys wants to KNOW when executables
    are being dropped and recompiled (each rebuild is seconds of
    latency), so this keeps hit/miss/eviction counts per cache and
    exposes them through :func:`compile_cache_stats`. Thread-safe: the
    builder runs outside the lock (tracing/compiling can take seconds;
    a racing duplicate build is wasted work, never wrong work)."""

    def __init__(self, name: str, builder: Callable, maxsize: int):
        self.name = name
        self._builder = builder
        self.maxsize = int(maxsize)
        self._d: "OrderedDict[tuple, Any]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = self.misses = self.evictions = 0
        _LRU_REGISTRY.append(self)

    def __call__(self, *key):
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                self.hits += 1
                return self._d[key]
            self.misses += 1
        # compile-cache MISS span: each rebuild is seconds of serving
        # latency — the event the observability plane must make visible
        with trace.span("infer.compile_miss", phase="compile",
                        cache=self.name):
            val = self._builder(*key)
        with self._lock:
            self._d[key] = val
            self._d.move_to_end(key)
            while len(self._d) > self.maxsize:
                self._d.popitem(last=False)
                self.evictions += 1
        return val

    def __len__(self) -> int:
        return len(self._d)

    def cache_clear(self) -> None:
        with self._lock:
            self._d.clear()

    def stats(self) -> Dict[str, int]:
        return {"size": len(self._d), "maxsize": self.maxsize,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions}


_LRU_REGISTRY: "list[_LRU]" = []


def _lru(name: str, maxsize: int):
    def wrap(fn):
        return _LRU(name, fn, maxsize)
    return wrap


def compile_cache_stats() -> Dict[str, Dict[str, int]]:
    """Per-cache ``{size, maxsize, hits, misses, evictions}`` for every
    memoized compiled-closure cache in this module. A growing
    ``evictions`` count under a steady workload means the working set
    of (shape, sampling) keys exceeds the cache — widen buckets or
    raise the cache size via :func:`set_compile_cache_size`."""
    return {c.name: c.stats() for c in _LRU_REGISTRY}


def set_compile_cache_size(maxsize: int) -> None:
    """Rebound every compiled-closure cache (existing entries beyond
    the new bound evict oldest-first)."""
    if maxsize < 1:
        raise ValueError(f"maxsize must be >= 1, got {maxsize}")
    for c in _LRU_REGISTRY:
        with c._lock:
            c.maxsize = int(maxsize)
            while len(c._d) > c.maxsize:
                c._d.popitem(last=False)
                c.evictions += 1


def _sample(logits, rng, temperature: float, top_k: Optional[int],
            top_p: Optional[float] = None, step=None, row_ids=None):
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    want_p = top_p is not None and top_p < 1.0
    if top_k is not None or want_p:
        # ONE descending sort serves both filters, and the keep mask is
        # scattered back by INDEX — a value threshold would keep every
        # token tied with the cutoff logit (uniform logits + top_p=0.5
        # would filter nothing)
        vocab = logits.shape[-1]
        idx = jnp.argsort(logits, axis=-1)[..., ::-1]
        desc = jnp.take_along_axis(logits, idx, axis=-1)
        keep_sorted = jnp.ones(desc.shape, bool)
        if top_k is not None:
            k = min(max(int(top_k), 1), vocab)
            keep_sorted &= jnp.arange(vocab) < k
        if want_p:
            # nucleus: the smallest prefix of descending-prob tokens
            # whose mass reaches top_p (the top token always stays —
            # its preceding cumulative mass is 0)
            probs = jax.nn.softmax(desc, axis=-1)
            before = jnp.cumsum(probs, axis=-1) - probs
            keep_sorted &= before < top_p
        keep = jnp.zeros(desc.shape, bool)
        keep = jnp.put_along_axis(keep, idx, keep_sorted, axis=-1,
                                  inplace=False)
        logits = jnp.where(keep, logits, -1e30)
    # per-ROW keys (fold_in by row index): row i's RANDOMNESS depends
    # only on (seed, step, i), never on the batch SHAPE — so a prompt's
    # sampled continuation no longer varies with pad-row count through
    # the RNG (packaging/lm.py pads length-buckets with copies of row
    # 0; a single batch-shaped categorical draw would give different
    # outputs for the same prompt+seed depending on the pad count).
    # ``step`` (scalar or per-row (B,)) folds the step index here too:
    # the blockwise engine passes the LOGICAL (pad-free) step so a
    # left-padded row draws the same stream as its unpadded run; the
    # stepwise engine pre-folds the step into ``rng`` (equivalent key
    # derivation — fold_in(fold_in(rng, t), i) either way).
    # Caveat: the LOGITS themselves are only batch-shape-invariant up
    # to the backend's reduction order — an ulp-level logit difference
    # near a probability boundary can still flip a draw on some
    # backends; the guarantee here is RNG invariance, not bitwise
    # forward-pass invariance.
    # ``row_ids`` (optional (B,) int32) replaces the physical row index
    # in the key derivation: the serving scheduler (tpuflow.serve)
    # assigns each REQUEST a stream id at admission, so a request's RNG
    # stream follows it to whatever decode slot it lands in — the
    # property that makes slot-level scheduling token-identical to the
    # wave-drained path under sampling.
    if row_ids is None:
        rows = jnp.arange(logits.shape[0])
    else:
        rows = jnp.asarray(row_ids, jnp.int32)
    if step is None:
        keys = jax.vmap(lambda i: jax.random.fold_in(rng, i))(rows)
    else:
        steps = jnp.broadcast_to(
            jnp.asarray(step, jnp.int32), rows.shape
        )
        keys = jax.vmap(
            lambda s, i: jax.random.fold_in(jax.random.fold_in(rng, s), i)
        )(steps, rows)
    return jax.vmap(
        lambda lg, k: jax.random.categorical(k, lg)
    )(logits, keys).astype(jnp.int32)


def generate(
    model,
    params,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    seed: int = 0,
    eos_id: Optional[int] = None,
    pad_lens=None,
    prefill_chunk: Optional[int] = None,
    decode_segment: int = 32,
    engine: str = "blockwise",
) -> jnp.ndarray:
    """Generate continuations for a batch of prompts.

    ``model``: a TransformerLM built with ``decode=False`` (its decode
    twin is derived here via ``.clone(decode=True)``); ``params``: its
    (unboxed) params. ``prompt``: (B, P) int32. Returns (B, P +
    max_new_tokens) int32 — prompts with sampled continuations; after a
    row emits ``eos_id`` its remaining positions repeat ``eos_id``.

    ``engine='blockwise'`` (default) prefills the prompt in
    ``ceil(P / prefill_chunk)`` multi-token forward passes
    (``prefill_chunk=None`` = the whole prompt in one pass; set it to
    bound the chunk's score-matrix VMEM) and then scans ONLY the
    sampling steps, in ``decode_segment``-step segments with an
    all-rows-done early exit between segments (``eos_id`` set). The
    scan trip count is bounded by the GENERATED length.

    ``pad_lens`` (blockwise only): optional (B,) int32 per-row count of
    LEFT pad slots — the bucketed-serving contract
    (tpuflow.packaging.lm). Row r's real prompt occupies positions
    ``pad_lens[r]:P``; pad slots are masked out of attention and the
    row's rotary positions / RNG steps are logical (pad-free), so its
    output tokens (at ``out[r, pad_lens[r]:]``) match the unpadded run.

    ``engine='stepwise'``: the original single-token scan over
    ``P + max_new_tokens - 1`` steps — the parity oracle.
    """
    dm = model.clone(decode=True, seq_axis=None)
    b, p = prompt.shape
    if p < 1:
        raise ValueError("prompt must have at least one token")
    if engine not in ("blockwise", "stepwise"):
        raise ValueError(
            f"engine must be 'blockwise' or 'stepwise', got {engine!r}"
        )
    if top_k is not None:
        vocab = getattr(model, "vocab_size", None)
        if top_k < 1 or (vocab is not None and top_k > vocab):
            raise ValueError(
                f"top_k={top_k} must be in [1, vocab_size"
                f"{'=' + str(vocab) if vocab is not None else ''}]"
            )
    if top_p is not None and not (0.0 < top_p <= 1.0):
        raise ValueError(f"top_p={top_p} must be in (0, 1]")
    prompt = jnp.asarray(prompt, jnp.int32)
    rng = jax.random.key(seed)
    max_len = p + max_new_tokens
    temperature = float(temperature)
    top_k = None if top_k is None else int(top_k)
    top_p = None if top_p is None else float(top_p)

    if pad_lens is not None:
        if engine != "blockwise":
            raise ValueError(
                "pad_lens (bucketed left-padding) requires "
                "engine='blockwise'"
            )
        import numpy as np

        pl = np.asarray(pad_lens, np.int32)
        if pl.shape != (b,):
            raise ValueError(
                f"pad_lens must have shape ({b},), got {pl.shape}"
            )
        if pl.min() < 0 or pl.max() >= p:
            raise ValueError(
                "pad_lens entries must be in [0, P): every row needs "
                "at least one real prompt token"
            )
        pad_lens = jnp.asarray(pl)

    if max_new_tokens < 1:
        return prompt

    if engine == "stepwise":
        run = _compiled_run(dm, b, p, max_len, temperature, top_k, top_p,
                            eos_id)
        with trace.span("infer.generate", engine="stepwise", rows=b,
                        prompt=p, new=max_new_tokens):
            return run(params, prompt, rng)

    chunk = p if prefill_chunk is None else max(1, int(prefill_chunk))
    seg = max(1, int(decode_segment))
    run = _compiled_blockwise(
        dm, b, p, max_len, temperature, top_k, top_p, eos_id,
        min(chunk, p), seg, pad_lens is not None,
    )
    # the prefill passes and decode segments run INSIDE this one
    # dispatch (host boundaries exist only in the serve engine — see
    # SlotPool's serve.prefill_join / serve.decode_segment spans); the
    # attrs carry the chunking so the span still answers "how was this
    # call shaped"
    with trace.span("infer.generate", engine="blockwise", rows=b,
                    prompt=p, new=max_new_tokens,
                    prefill_chunk=min(chunk, p), decode_segment=seg):
        if pad_lens is not None:
            return run(params, prompt, rng, pad_lens)
        return run(params, prompt, rng)


def clear_compile_cache() -> None:
    """Drop all memoized jitted decode closures (each holds a compiled
    executable and a model reference). A long-lived server cycling many
    distinct prompt shapes / sampling configs can call this to bound
    resident compile-cache growth; bucketing prompt lengths before
    calling :func:`generate` keeps the cache small in the first place
    (tpuflow.packaging.lm does this for the text surface). Growth is
    ALSO bounded passively: every cache here is a small LRU
    (:func:`compile_cache_stats` / :func:`set_compile_cache_size`)."""
    for c in _LRU_REGISTRY:
        c.cache_clear()


def _cache_zeros(dm, b: int, max_len: int):
    """Zero KV cache with the decode model's full-length cache struct,
    via eval_shape (no FLOPs). Built INSIDE the jitted runs so the
    memoized closures hold only ShapeDtypeStructs, not device buffers."""
    cache_shapes = jax.eval_shape(
        lambda: dm.init(
            {"params": jax.random.key(0)},
            jnp.zeros((b, max_len), jnp.int32),
        )["cache"]
    )
    return jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes
    )


@_lru("blockwise", maxsize=64)
def _compiled_blockwise(dm, b: int, p: int, max_len: int,
                        temperature: float, top_k: Optional[int],
                        top_p: Optional[float], eos_id: Optional[int],
                        chunk: int, seg: int, has_pads: bool):
    """The blockwise-prefill + early-exit decode engine, memoized on
    (model, shapes, sampling config, chunking) — a serving loop calling
    generate() per request with identical shapes compiles ONCE (flax
    modules are frozen dataclasses, so ``dm`` is a valid cache key).
    ``pad_lens`` is a RUNTIME argument (``has_pads`` only selects the
    signature), so one bucket shape serves every pad combination.
    Bounded at 64 entries; :func:`clear_compile_cache` empties it."""
    total = max_len - p - 1  # decode steps AFTER the prefill-sampled token

    def _impl(params, prompt, rng, pads):
        cache = _cache_zeros(dm, b, max_len)
        out = jnp.zeros((b, max_len), jnp.int32)
        out = lax.dynamic_update_slice(out, prompt, (0, 0))

        # ---- blockwise prefill: ceil(p/chunk) multi-token passes ----
        # (python loop over STATIC chunk offsets, unrolled at trace
        # time; every pass is an MXU-shaped matmul against the cache)
        logits = None
        for start in range(0, p, chunk):
            width = min(chunk, p - start)
            tok = lax.slice(prompt, (0, start), (b, start + width))
            logits, vars2 = dm.apply(
                {"params": params, "cache": cache}, tok,
                mutable=["cache"], pad_lens=pads,
            )
            cache = vars2["cache"]

        def logical(t):
            # sampling-step index as the row sees it: slot minus pads
            return t - pads if pads is not None else t

        # first generated token: sampled from the LAST prompt
        # position's prefill logits (slot p-1) — no scan step spent
        nxt = _sample(logits[:, -1], rng, temperature, top_k, top_p,
                      step=logical(jnp.int32(p - 1)))
        done = jnp.zeros((b,), jnp.bool_)
        if eos_id is not None:
            done = nxt == eos_id
        out = lax.dynamic_update_slice(out, nxt[:, None], (0, p))

        # ---- early-exit decode: segment scans under a while_loop ----
        def step(carry, t):
            cache, out, done = carry
            tok = lax.dynamic_slice(out, (0, t), (b, 1))
            lg, vars2 = dm.apply(
                {"params": params, "cache": cache}, tok,
                mutable=["cache"], pad_lens=pads,
            )
            nxt = _sample(lg[:, -1], rng, temperature, top_k, top_p,
                          step=logical(t))
            if eos_id is not None:
                nxt = jnp.where(done, jnp.int32(eos_id), nxt)
                done = done | (nxt == eos_id)
            out = lax.dynamic_update_slice(out, nxt[:, None], (0, t + 1))
            return (vars2["cache"], out, done), None

        def run_seg(cache, out, done, t0, n):
            (cache, out, done), _ = lax.scan(
                lambda c, i: step(c, t0 + i), (cache, out, done),
                jnp.arange(n),
            )
            return cache, out, done

        if total > 0:
            seg_n = min(seg, total)
            nfull, rem = divmod(total, seg_n)
            if eos_id is None:
                # no EOS → no early exit possible: one flat scan
                cache, out, done = run_seg(cache, out, done,
                                           jnp.int32(p), total)
            else:
                def cond(c):
                    k, _cache, _out, done = c
                    return (k < nfull) & ~jnp.all(done)

                def body(c):
                    k, cache, out, done = c
                    cache, out, done = run_seg(
                        cache, out, done, p + k * seg_n, seg_n
                    )
                    return (k + 1, cache, out, done)

                _, cache, out, done = lax.while_loop(
                    cond, body, (jnp.int32(0), cache, out, done)
                )
                if rem:
                    cache, out, done = lax.cond(
                        jnp.all(done),
                        lambda c: c,
                        lambda c: run_seg(*c, p + nfull * seg_n, rem),
                        (cache, out, done),
                    )

        if eos_id is not None:
            # early exit leaves post-EOS slots unwritten (zeros); the
            # contract says they repeat eos_id — backfill every slot
            # strictly after a row's first generated EOS (a no-op for
            # slots the scan already filled)
            gen = out[:, p:]
            hit = (gen == eos_id).astype(jnp.int32)
            after = jnp.cumsum(hit, axis=1) - hit
            gen = jnp.where(after > 0, jnp.int32(eos_id), gen)
            out = jnp.concatenate([out[:, :p], gen], axis=1)
        return out

    if has_pads:
        @_rjit(key="infer.blockwise")
        def run(params, prompt, rng, pad_lens):
            return _impl(params, prompt, rng, pad_lens)
    else:
        @_rjit(key="infer.blockwise")
        def run(params, prompt, rng):
            return _impl(params, prompt, rng, None)

    return run


# --------------------------------------------------------------------
# Serve engine: segment-granular resume + per-slot cache writes.
#
# The building blocks of tpuflow.serve's slot-level continuous
# batching. A SLOT POOL is a fixed (slots, length) decode state —
# KV cache + token buffer — that the scheduler drives in SEGMENTS of a
# fixed step count, with control returning to the host at every
# boundary. All rows share ONE physical write position t (the scalar
# flax cache_index), so the state machine stays compile-stable: exactly
# two executables per pool, regardless of how requests come and go.
# What makes rows independent anyway is the bucketed-serving machinery
# above: a request JOINING at boundary t is LEFT-padded so its prompt
# ENDS at position t (pad_lens[row] = t - prompt_len + 1), its rotary
# positions / attention window / RNG steps are logical (pad-free), and
# its per-request ``stream_id`` replaces the physical row in the
# sampling key — so the tokens it generates are identical to the same
# request served in a wave-drained batch (the parity the scheduler
# tests pin).
#
# Per-slot cache writes: the join executable runs ONE (slots,
# bucket-1)-shaped prefill pass over the tail window ending at t and
# merges the resulting KV rows into the live cache ONLY for joining
# rows (everything else keeps its in-flight state). The last prompt
# token is deliberately left to the next decode step — it appends that
# token's KV at position t exactly like every other row's step, which
# is what lets joined and in-flight rows share one step function.
# Stale KV from a slot's previous occupant needs no zeroing: positions
# before the new request's pads and after the current index are both
# masked out of every attention read (CausalAttention decode mask).


def _set_cache_index(cache, value):
    """Rewrite every scalar ``cache_index`` leaf to ``value`` (the
    decode-attention cache tree is (B, ...) arrays + one scalar index
    per layer, so ndim==0 identifies the index leaves)."""
    v = jnp.asarray(value, jnp.int32)
    return jax.tree.map(lambda leaf: v if leaf.ndim == 0 else leaf, cache)


def _merge_rows(new, old, row_mask):
    """Per-row select between two identically-shaped cache/state trees:
    rows where ``row_mask`` is True take ``new``. Scalar leaves (the
    cache indices) take ``new`` unconditionally — join and decode leave
    them equal by construction."""
    def pick(n, o):
        if n.ndim == 0:
            return n
        m = row_mask.reshape((-1,) + (1,) * (n.ndim - 1))
        return jnp.where(m, n, o)

    return jax.tree.map(pick, new, old)


def serve_pool_arrays(model, slots: int, length: int, kv_spec=None):
    """Fresh device state for one slot pool: (KV cache, token buffer).
    ``length`` is the pool's whole physical horizon (bucket + decode
    room); the token buffer is (slots, length) int32 zeros.

    ``kv_spec=None`` (the contiguous cache): the cache is the decode
    twin's full-length per-row buffer — memory is ``slots × length``
    whether or not tokens exist.

    ``kv_spec`` set (any object with ``pages``/``page_size``/``quant``
    attributes — :class:`tpuflow.serve.pages.PagedKVSpec`): the cache
    is a PAGED pool of ``kv_spec.pages`` fixed-size pages per layer,
    shared by every row (and every bucket) through per-call page
    tables — memory scales with pages actually allocated, and the pool
    is batch-size-independent (ONE store serves all slot pools; see
    MIGRATION.md for this signature change)."""
    dm = _serve_decode_model(model, kv_spec)
    arrays = (_cache_zeros(dm, slots, length),
              jnp.zeros((slots, length), jnp.int32))
    _mem.tag("kv_pages", arrays)  # device-buffer ledger (ISSUE 7)
    return arrays


# --------------------------------------------------------------------
# Paged serve engine: page-indexed gather/scatter variants of the
# serve functions above (ISSUE 6). The contiguous pool gives every
# slot `length` KV positions whether or not tokens exist; here the KV
# store is a process-wide pool of fixed-size PAGES and each slot maps
# its logical positions onto physical pages through a per-call
# ``page_table`` (vLLM's PagedAttention idea on the blockwise engine).
# Differences from the contiguous serve engine:
#
# - rows live at their LOGICAL positions (physical == logical, no
#   left-pads, no shared scalar cache_index): each row carries its own
#   write position, so admission is never quantized to a shared
#   horizon and a pool needs no reset/rounds machinery;
# - page 0 is the RESERVED WRITE SINK: masked writes (empty slots,
#   done rows, prefill tails past a row's width) are redirected there
#   instead of corrupting live pages — which is what makes pages
#   SHARABLE between rows (copy-on-write prefix reuse, serve/pages.py);
# - the join executable is WIDTH-BUCKETED: a request admitted with a
#   prefix-cache hit prefills only its uncached suffix through the
#   narrowest compiled window that fits (width=1 = token-write only,
#   no model pass at all) — the prefill-skip that makes shared system
#   prompts cheap;
# - sampling streams are unchanged (`_sample` row_ids + logical
#   steps), so paged outputs stay token-identical to the wave oracle.


def _serve_decode_model(model, kv_spec=None):
    if kv_spec is None:
        return model.clone(decode=True, seq_axis=None)
    return model.clone(
        decode=True, seq_axis=None, kv_pages=int(kv_spec.pages),
        kv_page_size=int(kv_spec.page_size), kv_quant=kv_spec.quant,
        # fused paged-decode kernel policy (ISSUE 11): None = auto
        # (TPU only); kv_spec may predate the field (duck-typed specs)
        paged_kernel=getattr(kv_spec, "kernel", None),
    )


def paged_kv_arrays(model, kv_spec, component: str = "kv_pages"):
    """Fresh device page store for ``model``: the per-layer page pools
    ((pages, KVH, page_size, head_dim) keys/values, + (pages,
    page_size) scale vectors under ``quant='int8'``). Batch-size
    independent — ONE store is threaded through every pool's join and
    segment executables. ``component`` names the store in the device-
    buffer ledger (``kv_pages`` for the target store, ``kv_draft`` for
    a speculative-decoding draft store)."""
    dm = _serve_decode_model(model, kv_spec)
    store = _cache_zeros(dm, 1, 1)
    _mem.tag(component, store)  # device-buffer ledger (ISSUE 7)
    return store


def paged_page_bytes(kv_cache) -> int:
    """Device bytes per page across all layers/leaves of a store built
    by :func:`paged_kv_arrays` — the unit of the serve runtime's KV
    memory accounting (tools/kv_memory_report.py)."""
    leaves = jax.tree.leaves(kv_cache)
    if not leaves:
        return 0
    pages = leaves[0].shape[0]
    return sum(leaf.nbytes for leaf in leaves) // pages


def paged_join_fn(model, kv_spec, slots: int, out_len: int,
                  n_row_pages: int, width: int):
    """Compiled paged admission: write each joining row's uncached
    prompt SUFFIX and prefill its KV through the page table.

    Returns ``join(params, cache, out, tokens, starts, widths,
    page_table) -> (cache, out)``:

    - ``tokens`` (slots, width) int32: row r's suffix tokens
      (prompt[m_r:p_r], left-justified, zero-padded right) where m_r
      is its prefix-cache match length; only ``widths[r]`` entries are
      real (0 = row not joining);
    - ``starts`` (slots,) int32: m_r — the row's KV length before this
      join (its first uncached position);
    - the LAST suffix token (the final prompt token) is written into
      ``out`` but its KV is left to the first decode step, exactly
      like the contiguous join — so ``widths[r] - 1`` positions
      prefill, and ``width == 1`` is the full-prefix-hit fast path
      that runs NO model pass at all.

    Non-joining rows (width 0) keep their buffers: token writes are
    masked per-position and KV writes ride the attention layer's
    write-mask → page-0 sink redirection."""
    dm = _serve_decode_model(model, kv_spec)
    if width < 1:
        raise ValueError(f"width must be >= 1, got {width}")
    return _compiled_paged_join(dm, int(slots), int(out_len),
                                int(n_row_pages), int(width))


# keyspace is (bucket × pow2-width): ~log2(bucket)+2 entries PER
# bucket, so the bound is several times the per-bucket caches' — an
# eviction here recompiles on the admission hot path
@_lru("paged_join", maxsize=128)
def _compiled_paged_join(dm, b: int, out_len: int, n_row_pages: int,
                         w: int):
    # the page store is DONATED (ISSUE 11): XLA updates it in place
    # instead of copying the whole pool per call, so join cost stops
    # scaling with kv_pages. Contract: the caller must drop its
    # reference (reassign from the return value) — PagedSlotPool.join
    # and PagedKV hold the only references and do exactly that. ``out``
    # is NOT donated: the speculative draft join reads the target
    # join's out afterwards (and it is a small int32 buffer anyway).
    @_rjit(key="infer.paged_join", donate_argnums=(1,))
    def join(params, cache, out, tokens, starts, widths, page_table):
        idx = starts[:, None] + jnp.arange(w, dtype=jnp.int32)
        live = jnp.arange(w)[None, :] < widths[:, None]
        idxc = jnp.clip(idx, 0, out_len - 1)
        cur = jnp.take_along_axis(out, idxc, axis=1)
        out = jnp.put_along_axis(out, idxc, jnp.where(live, tokens, cur),
                                 axis=1, inplace=False)
        if w > 1:
            # prefill the suffix MINUS its last token (that token's KV
            # is appended by the next decode step, which also yields
            # the logits the first sample needs)
            chunk = lax.slice(tokens, (0, 0), (b, w - 1))
            wm = jnp.arange(w - 1)[None, :] < (widths[:, None] - 1)
            _, vars2 = dm.apply(
                {"params": params, "cache": cache}, chunk,
                mutable=["cache"], page_table=page_table,
                write_pos=starts, write_mask=wm,
            )
            cache = vars2["cache"]
        return cache, out

    return join


def _rows_view(cache, page_table):
    """Hoisted gather (ISSUE 11): turn a paged cache collection into a
    dense per-row WINDOW collection — every ``key_pages``/
    ``value_pages`` leaf (npages, KVH, ps, D) becomes ``key_rows``/
    ``value_rows`` (B, KVH, W*ps, D) gathered through ``page_table``
    (B, W). Paid ONCE per decode segment instead of once per step; the
    rowwise branch of CausalAttention consumes the result."""
    ren = {"key_pages": "key_rows", "value_pages": "value_rows"}

    def walk(node):
        out = {}
        for name, leaf in node.items():
            if name in ren:
                b, w = page_table.shape
                ps, d = leaf.shape[2], leaf.shape[3]
                kvh = leaf.shape[1]
                g = leaf[page_table]  # (B, W, KVH, ps, D)
                out[ren[name]] = g.transpose(0, 2, 1, 3, 4).reshape(
                    b, kvh, w * ps, d)
            elif isinstance(leaf, dict):
                out[name] = walk(leaf)
            else:  # scale leaves etc. are absent on this path (no int8)
                out[name] = leaf
        return out

    return walk(dict(cache))


def _rows_scatter_back(cache, rows, page_table, pos0, kv_limit, done0,
                       seg: int):
    """Hoisted scatter (ISSUE 11): write back the pages a segment
    could have touched — positions ``[pos0, min(pos0+seg, kv_limit))``
    per row, i.e. at most ``(seg-1)//ps + 2`` pages — from the dense
    window into the store. Written pages are row-EXCLUSIVE (allocator
    invariant: shared prefix pages are read-only and live strictly
    below the write range), rows done at segment entry redirect to the
    sink, and untouched window slots scatter back their own gathered
    content (identity)."""
    ren = {"key_rows": "key_pages", "value_rows": "value_pages"}

    def walk(cnode, rnode):
        out = {}
        for name, leaf in cnode.items():
            if name in ("key_pages", "value_pages"):
                rname = ("key_rows" if name == "key_pages"
                         else "value_rows")
                dense = rnode[rname]
                b, w = page_table.shape
                ps = leaf.shape[2]
                kvh, d = leaf.shape[1], leaf.shape[3]
                pages = dense.reshape(b, kvh, w, ps, d).transpose(
                    0, 2, 1, 3, 4)  # (B, W, KVH, ps, D)
                j0 = pos0 // ps
                # last position actually writable this segment
                last = jnp.minimum(pos0 + seg, kv_limit) - 1
                j1 = last // ps
                n_touch = (seg - 1) // ps + 2
                st = leaf
                for t in range(n_touch):
                    j = j0 + t
                    jc = jnp.clip(j, 0, w - 1)
                    valid = (j <= j1) & (j < w) & ~done0
                    pg = jnp.where(
                        valid,
                        jnp.take_along_axis(page_table, jc[:, None],
                                            axis=1)[:, 0],
                        0,
                    )
                    content = jnp.take_along_axis(
                        pages, jc[:, None, None, None, None], axis=1
                    )[:, 0]  # (B, KVH, ps, D)
                    st = st.at[pg].set(content)
                out[name] = st
            elif isinstance(leaf, dict):
                out[name] = walk(leaf, rnode[name])
            else:
                out[name] = leaf
        return out

    return walk(dict(cache), dict(rows))


def paged_segment_fn(model, kv_spec, slots: int, out_len: int,
                     n_row_pages: int, seg: int, temperature: float,
                     top_k: Optional[int], top_p: Optional[float],
                     eos_id: Optional[int],
                     table_width: Optional[int] = None):
    """Compiled paged decode segment: advance every row ``seg`` steps
    at its OWN position, then return control to the host.

    Returns ``segment(params, cache, out, done, pos, kv_limit,
    last_tok, stream_ids, rng, page_table) -> (cache, out, done,
    toks)``:

    - ``pos`` (slots,) int32: each row's KV length = the index of its
      next input token (rows are NOT aligned to a shared boundary);
    - ``kv_limit`` (slots,) int32: first KV position the row must NOT
      write (p + max_new - 1) — writes at/after it go to the page-0
      sink, so a row never needs pages past its own budget;
    - ``last_tok`` (slots,) int32: index of the row's final allowed
      token (p + max_new - 1); emitting it sets ``done``;
    - ``toks`` (slots, seg): the per-row token windows written this
      segment (``out[r, pos[r]+1 : pos[r]+seg+1]``).

    ``table_width`` (ISSUE 11, the hoisted fast path): compile the
    segment for a (slots, table_width) page-table window — the pages
    are gathered into dense per-row (B, KVH, W*ps, D) windows ONCE,
    the ``seg`` steps run against the dense window (the rowwise branch
    of CausalAttention — per-step cost is the contiguous path's, no
    per-step gather/scatter), and the pages the segment wrote scatter
    back ONCE at the end. The caller slices its page table to the
    narrowest width covering every live row's need this segment
    (:meth:`~tpuflow.serve.slots.PagedSlotPool.segment_width`), so
    young rows attend over short windows. ``None`` keeps the per-step
    paged path (the int8 store, and the fused-kernel path where the
    kernel IS the per-step fast path).

    MoE models (``model.n_experts > 0``, ISSUE 18) return ONE extra
    output: ``expert_load`` (n_experts,) float32 — routed-token counts
    summed over the segment's LIVE rows and steps (each MoE block's
    sown top-k assignment mass, finished rows masked out). The serve
    engine harvests it for the per-expert gauges and the host-side
    capacity admission gate; dense models keep the 4-tuple signature
    unchanged."""
    dm = _serve_decode_model(model, kv_spec)
    return _compiled_paged_segment(
        dm, int(slots), int(out_len), int(n_row_pages), int(seg),
        float(temperature),
        None if top_k is None else int(top_k),
        None if top_p is None else float(top_p),
        None if eos_id is None else int(eos_id),
        None if table_width is None else int(table_width),
    )


@_lru("paged_segment", maxsize=64)
def _compiled_paged_segment(dm, b: int, out_len: int, n_row_pages: int,
                            seg: int, temperature: float,
                            top_k: Optional[int], top_p: Optional[float],
                            eos_id: Optional[int],
                            table_width: Optional[int] = None):
    fill = jnp.int32(eos_id if eos_id is not None else 0)
    hoist = table_width is not None
    # MoE load harvest (ISSUE 18): route the sown "moe" collection out
    # through the scan carry — gated on the model so dense pools keep
    # their exact signature (and executables)
    n_exp = int(getattr(dm, "n_experts", 0) or 0)

    # donated page store (ISSUE 11): the KV writes happen in place —
    # this is what killed the O(kv_pages) segment-cost coupling the
    # PR 6 KNOWN LIMIT documented (the functional update used to copy
    # the whole store every decode step, even on XLA:CPU). With
    # ``table_width`` the gather/scatter is additionally HOISTED to
    # the segment boundary (see paged_segment_fn).
    @_rjit(key="infer.paged_segment", donate_argnums=(1,))
    def segment(params, cache, out, done, pos0, kv_limit, last_tok,
                stream_ids, rng, page_table):
        if hoist:
            rows = _rows_view(cache, page_table)

        def step(carry, i):
            if n_exp:
                kv, out, done, load = carry
            else:
                kv, out, done = carry
            pos = pos0 + i
            posc = jnp.clip(pos, 0, out_len - 1)
            tok = jnp.take_along_axis(out, posc[:, None], axis=1)
            wm = (~done & (pos < kv_limit))[:, None]
            mut = ["cache", "moe"] if n_exp else ["cache"]
            if hoist:
                lg, vars2 = dm.apply(
                    {"params": params, "cache": kv}, tok,
                    mutable=mut, write_pos=pos, write_mask=wm,
                )
            else:
                lg, vars2 = dm.apply(
                    {"params": params, "cache": kv}, tok,
                    mutable=mut, page_table=page_table,
                    write_pos=pos, write_mask=wm,
                )
            if n_exp:
                # each MoE block sowed its (B, 1, E) top-k assignment
                # mass; finished/over-limit rows run the matmuls (the
                # batch is fixed-shape) but must not count as load
                per_row = sum(leaf.sum(axis=1)
                              for leaf in jax.tree.leaves(
                                  vars2.get("moe", {})))
                load = load + jnp.sum(
                    jnp.where(wm, per_row, 0.0), axis=0)
            # the sampling step is the row's LOGICAL position — the
            # same value the wave oracle derives as t - pad_lens — so
            # a request's RNG stream is identical in both engines
            nxt = _sample(lg[:, -1], rng, temperature, top_k, top_p,
                          step=pos, row_ids=stream_ids)
            nxt = jnp.where(done, fill, nxt)
            done = done | (pos + 1 >= last_tok)
            if eos_id is not None:
                done = done | (nxt == eos_id)
            outw = jnp.clip(pos + 1, 0, out_len - 1)
            out = jnp.put_along_axis(out, outw[:, None], nxt[:, None],
                                     axis=1, inplace=False)
            if n_exp:
                return (vars2["cache"], out, done, load), None
            return (vars2["cache"], out, done), None

        kv0 = rows if hoist else cache
        if n_exp:
            carry0 = (kv0, out, done,
                      jnp.zeros((n_exp,), jnp.float32))
            (kv_out, out, done2, load), _ = lax.scan(
                step, carry0, jnp.arange(seg)
            )
        else:
            carry0 = (kv0, out, done)
            (kv_out, out, done2), _ = lax.scan(
                step, carry0, jnp.arange(seg)
            )
        if hoist:
            cache = _rows_scatter_back(cache, kv_out, page_table,
                                       pos0, kv_limit, done, seg)
        else:
            cache = kv_out
        tix = jnp.clip(pos0[:, None] + 1 + jnp.arange(seg)[None, :],
                       0, out_len - 1)
        toks = jnp.take_along_axis(out, tix, axis=1)
        if n_exp:
            return cache, out, done2, toks, load
        return cache, out, done2, toks

    return segment


# --------------------------------------------------------------------
# Speculative decoding (ISSUE 9): draft-proposed, blockwise-verified,
# ORACLE-PARITY acceptance over the paged serve engine.
#
# One speculative ROUND replaces `k+1` sequential target decode steps:
#
# - the DRAFT step fn runs k single-token steps of a small TransformerLM
#   (its KV lives in a second page store indexed by the SAME per-row
#   page table, so pages are allocated/released/forked exactly once);
# - the VERIFY fn is ONE blockwise target pass over the k+1 tokens
#   [current input, d_1..d_k] — the same multi-token paged machinery
#   the width-bucketed join prefill compiles, so the verify window is
#   just another prefill width (k+1 rides the pow2 menu sizes);
# - the ACCEPTANCE kernel computes, at every verified position, the
#   exact token the stepwise oracle would emit — `_sample` with the
#   row's logical step and admission-index `stream_id`, the SAME key
#   derivation every other engine here uses — and accepts the draft's
#   proposals only while they match. The emitted sequence is therefore
#   the oracle's sequence BY CONSTRUCTION, greedy AND sampled (the
#   draft proposes with the same per-(step, stream) keys, so a draft
#   whose distribution tracks the target's reproduces the oracle's
#   categorical draw through the shared Gumbel noise — that coupling
#   is what acceptance rate measures); draft quality can only change
#   THROUGHPUT, never tokens.
#
# Rollback is free: rejected draft tokens wrote target/draft KV at
# positions above the row's new write_pos, which every attention read
# masks (key_pos <= query pos) and the next round's verify REWRITES —
# a per-row write_pos rewind, no page churn, no copies. Pages were
# allocated for the row's full budget at admission, so rounds never
# touch the allocator.


def _spec_accept(drafts, xs, done, spec_on, pos0, last_tok,
                 eos_id: Optional[int]):
    """The acceptance kernel (pure jnp; unit-tested directly).

    ``drafts`` (B, k): the draft's proposals; ``xs`` (B, k+1): the
    oracle token at each verified position (``xs[:, i]`` is what the
    stepwise oracle emits after the prefix ending at position
    ``pos0 + i``). Returns ``(n_acc, n_emit, new_done)``:

    - ``n_acc``: leading proposals equal to the oracle's tokens
      (``spec_on`` False forces 0 — that row runs as a plain decode
      step inside the same batch);
    - ``n_emit``: tokens actually emitted this round — ``n_acc + 1``
      (the correction/bonus token is always an oracle token), clamped
      to the row's remaining budget and truncated at the first
      generated EOS; ``done`` rows emit nothing;
    - ``new_done``: rows that hit their budget or emitted the EOS.
    """
    k = drafts.shape[1]
    w = k + 1
    match = (drafts == xs[:, :k]) & spec_on[:, None]
    n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
    n_budget = jnp.clip(last_tok - pos0, 0, w)
    n_emit = jnp.minimum(n_acc + 1, n_budget)
    if eos_id is not None:
        is_eos = xs == eos_id
        first = jnp.argmax(is_eos, axis=1)
        has = jnp.any(is_eos, axis=1)
        n_emit = jnp.where(has, jnp.minimum(n_emit, first + 1), n_emit)
    n_emit = jnp.where(done, 0, n_emit)
    new_done = done | (~done & (pos0 + n_emit >= last_tok))
    if eos_id is not None:
        new_done = new_done | (has & (first < n_emit))
    return n_acc, n_emit, new_done


def spec_draft_fn(draft_model, kv_spec, slots: int, out_len: int,
                  n_row_pages: int, k: int, temperature: float,
                  top_k: Optional[int], top_p: Optional[float]):
    """Compiled draft proposer: ``k`` single-token steps of the draft
    model at each row's own position, through the draft page store.

    Returns ``draft(params, dcache, out, done, pos0, kv_limit,
    spec_on, stream_ids, rng, page_table) -> (dcache, drafts)`` with
    ``drafts`` (slots, k) int32. Proposals use the SAME
    ``_sample`` key derivation as the oracle (logical step +
    ``stream_id``), so a draft that tracks the target reproduces the
    oracle's draw through the shared noise. ``spec_on`` False masks a
    row's draft KV writes (its proposals are discarded by the
    acceptance kernel anyway)."""
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    ddm = _serve_decode_model(draft_model, kv_spec)
    return _compiled_spec_draft(
        ddm, int(slots), int(out_len), int(n_row_pages), int(k),
        float(temperature),
        None if top_k is None else int(top_k),
        None if top_p is None else float(top_p),
    )


@_lru("spec_draft", maxsize=32)
def _compiled_spec_draft(ddm, b: int, out_len: int, n_row_pages: int,
                         k: int, temperature: float,
                         top_k: Optional[int], top_p: Optional[float]):
    # draft page store donated (ISSUE 11) — same in-place contract as
    # the segment fn; ``out`` is read-only here (verify reads it next)
    @_rjit(key="infer.spec_draft", donate_argnums=(1,))
    def draft(params, dcache, out, done, pos0, kv_limit, spec_on,
              stream_ids, rng, page_table):
        posc = jnp.clip(pos0, 0, out_len - 1)
        tok0 = jnp.take_along_axis(out, posc[:, None], axis=1)[:, 0]
        # step 0 is a fixed 2-wide CATCH-UP window [prev, current]:
        # after a fully-accepted round the bonus token advanced the
        # row past the draft's written frontier (the draft generated
        # that token but never consumed it), leaving exactly ONE
        # position of draft KV unwritten. Rewriting an already-written
        # slot is value-idempotent — KV at a position is a function of
        # that position's token alone — so a constant-width window
        # covers both cases with no per-row gap tracking. Row at
        # position 0 (1-token prompt, full-hit join): the prev slot
        # clamps and its write masks out.
        poscm1 = jnp.clip(pos0 - 1, 0, out_len - 1)
        tokm1 = jnp.take_along_axis(out, poscm1[:, None], axis=1)[:, 0]
        live = ~done & spec_on
        wm0 = jnp.stack(
            [live & (pos0 - 1 >= 0) & (pos0 - 1 < kv_limit),
             live & (pos0 < kv_limit)], axis=1)
        lg, vars2 = ddm.apply(
            {"params": params, "cache": dcache},
            jnp.stack([tokm1, tok0], axis=1),
            mutable=["cache"], page_table=page_table,
            write_pos=pos0 - 1, write_mask=wm0,
        )
        dcache = vars2["cache"]
        d1 = _sample(lg[:, -1], rng, temperature, top_k, top_p,
                     step=pos0, row_ids=stream_ids)

        def step(carry, i):
            dcache, tok = carry
            pos = pos0 + i
            wm = (live & (pos < kv_limit))[:, None]
            lg, vars2 = ddm.apply(
                {"params": params, "cache": dcache}, tok[:, None],
                mutable=["cache"], page_table=page_table,
                write_pos=pos, write_mask=wm,
            )
            nxt = _sample(lg[:, -1], rng, temperature, top_k, top_p,
                          step=pos, row_ids=stream_ids)
            return (vars2["cache"], nxt), nxt

        if k > 1:
            (dcache, _), rest = lax.scan(step, (dcache, d1),
                                         jnp.arange(1, k))
            drafts = jnp.concatenate([d1[:, None], rest.T], axis=1)
        else:
            drafts = d1[:, None]
        return dcache, drafts  # (B, k)

    return draft


def spec_verify_fn(model, kv_spec, slots: int, out_len: int,
                   n_row_pages: int, k: int, temperature: float,
                   top_k: Optional[int], top_p: Optional[float],
                   eos_id: Optional[int]):
    """Compiled blockwise verify + oracle-parity acceptance: ONE
    target pass over the k+1 positions ``[input, d_1..d_k]``, then
    :func:`_spec_accept`.

    Returns ``verify(params, cache, out, drafts, done, pos0, kv_limit,
    last_tok, spec_on, stream_ids, rng, page_table) -> (cache, out,
    done, xs, n_emit, n_acc)`` where ``xs`` (slots, k+1) holds the
    oracle tokens (``xs[r, :n_emit[r]]`` were emitted and written into
    ``out`` at ``pos0[r]+1 ..``). Target KV for the verified window is
    written through the page table exactly like a join prefill;
    positions the acceptance rejects hold garbage ABOVE the row's new
    write position — masked by every read and rewritten next round
    (the free rollback)."""
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    dm = _serve_decode_model(model, kv_spec)
    return _compiled_spec_verify(
        dm, int(slots), int(out_len), int(n_row_pages), int(k),
        float(temperature),
        None if top_k is None else int(top_k),
        None if top_p is None else float(top_p),
        None if eos_id is None else int(eos_id),
    )


@_lru("spec_verify", maxsize=32)
def _compiled_spec_verify(dm, b: int, out_len: int, n_row_pages: int,
                          k: int, temperature: float,
                          top_k: Optional[int], top_p: Optional[float],
                          eos_id: Optional[int]):
    w = k + 1

    # target page store donated (ISSUE 11): the verify pass is a paged
    # join by construction — it rides the same in-place fast path
    @_rjit(key="infer.spec_verify", donate_argnums=(1,))
    def verify(params, cache, out, drafts, done, pos0, kv_limit,
               last_tok, spec_on, stream_ids, rng, page_table):
        posc = jnp.clip(pos0, 0, out_len - 1)
        tok0 = jnp.take_along_axis(out, posc[:, None], axis=1)[:, 0]
        vtoks = jnp.concatenate([tok0[:, None], drafts], axis=1)
        vpos = pos0[:, None] + jnp.arange(w, dtype=jnp.int32)
        vwm = (~done)[:, None] & (vpos < kv_limit[:, None])
        lg, vars2 = dm.apply(
            {"params": params, "cache": cache}, vtoks,
            mutable=["cache"], page_table=page_table,
            write_pos=pos0, write_mask=vwm,
        )
        cache = vars2["cache"]
        # the oracle token at every verified position: logits at
        # pos0+i depend only on the prefix through pos0+i, which by
        # induction is the oracle's prefix for all i <= n_acc — the
        # key derivation is bit-for-bit the plain segment fn's
        xs = jnp.stack(
            [_sample(lg[:, i], rng, temperature, top_k, top_p,
                     step=pos0 + i, row_ids=stream_ids)
             for i in range(w)], axis=1)
        n_acc, n_emit, new_done = _spec_accept(
            drafts, xs, done, spec_on, pos0, last_tok, eos_id)
        eidx = pos0[:, None] + 1 + jnp.arange(w, dtype=jnp.int32)
        elive = jnp.arange(w)[None, :] < n_emit[:, None]
        eidxc = jnp.clip(eidx, 0, out_len - 1)
        cur = jnp.take_along_axis(out, eidxc, axis=1)
        out = jnp.put_along_axis(out, eidxc, jnp.where(elive, xs, cur),
                                 axis=1, inplace=False)
        return cache, out, new_done, xs, n_emit, n_acc

    return verify


@_rjit(key="infer.paged_copy", donate_argnums=(0,))
def _paged_copy_jit(cache, src, dst):
    # donated: a COW fork copies WIDTH pages, not the whole store
    return jax.tree.map(lambda a: a.at[dst].set(a[src]), cache)


def paged_copy(kv_cache, src_pages, dst_pages, width: int = 8):
    """Copy-on-write device fork: duplicate whole pages across every
    layer/leaf (``cache[dst[i]] = cache[src[i]]``). Pairs are padded
    to fixed ``width`` chunks with 0→0 no-ops (page 0 is the write
    sink) so the executable compiles once per store shape, not once
    per fork count."""
    n = len(src_pages)
    if n != len(dst_pages):
        raise ValueError("src/dst page lists must have equal length")
    for ofs in range(0, n, width):
        s = list(src_pages[ofs:ofs + width])
        d = list(dst_pages[ofs:ofs + width])
        pad = width - len(s)
        s = jnp.asarray(s + [0] * pad, jnp.int32)
        d = jnp.asarray(d + [0] * pad, jnp.int32)
        kv_cache = _paged_copy_jit(kv_cache, s, d)
    return kv_cache


# --------------------------------------------------------------------
# KV-page wire transport (ISSUE 14, prefill/decode disaggregation):
# whole pages move between PROCESSES — a prefill replica gathers its
# page chain to host bytes, the decode replica scatters the payloads
# into its own store. Both directions ride fixed-width chunks (the
# paged_copy idiom) so each compiles once per store shape: gather pads
# with sink-page reads the host side drops, scatter pads with
# sink-page writes nobody reads. The serialization half (per-page
# CRC32, chunk keys, header validation) lives in serve/pages.py.


@_rjit(key="infer.paged_gather")
def _paged_gather_jit(cache, pages):
    return jax.tree.map(lambda leaf: leaf[pages], cache)


def paged_gather(kv_cache, page_ids, width: int = 8):
    """Pull whole pages to the HOST across every layer/leaf of a store
    built by :func:`paged_kv_arrays`: returns a numpy pytree mirroring
    the cache with leading dim ``len(page_ids)`` (page ``page_ids[i]``
    at index i). Page ids are padded to fixed ``width`` chunks with
    sink-page reads (dropped host-side) so the gather is ONE compiled
    executable per store shape regardless of chain length."""
    import numpy as np

    n = len(page_ids)
    if n == 0:
        return jax.tree.map(
            lambda leaf: np.zeros((0,) + leaf.shape[1:],
                                  np.dtype(str(leaf.dtype))), kv_cache)
    outs = []
    for ofs in range(0, n, width):
        chunk = [int(p) for p in page_ids[ofs:ofs + width]]
        pad = width - len(chunk)
        idx = jnp.asarray(chunk + [0] * pad, jnp.int32)
        got = jax.device_get(_paged_gather_jit(kv_cache, idx))
        if pad:
            got = jax.tree.map(lambda a, k=width - pad: a[:k], got)
        outs.append(got)
    if len(outs) == 1:
        return outs[0]
    return jax.tree.map(lambda *xs: np.concatenate(xs, axis=0), *outs)


@_rjit(key="infer.paged_store", donate_argnums=(0,))
def _paged_store_jit(cache, payload, pages):
    return jax.tree.map(
        lambda leaf, vals: leaf.at[pages].set(vals.astype(leaf.dtype)),
        cache, payload)


def paged_store_pages(kv_cache, page_ids, payload, width: int = 8):
    """Scatter HOST page payloads into the store in place (the store
    is DONATED — callers must reassign from the return value, the
    ISSUE 11 contract): ``payload`` is a pytree mirroring the cache
    with leading dim ``len(page_ids)``; page ``page_ids[i]`` receives
    payload index i across every leaf. Ids are padded to fixed
    ``width`` chunks redirected at the sink page 0 (garbage nobody
    reads), so the scatter compiles once per store shape."""
    import numpy as np

    n = len(page_ids)
    for ofs in range(0, n, width):
        chunk = [int(p) for p in page_ids[ofs:ofs + width]]
        k = len(chunk)
        pad = width - k

        def _slice(a):
            part = np.asarray(a[ofs:ofs + k])
            if pad:
                part = np.concatenate(
                    [part, np.zeros((pad,) + part.shape[1:],
                                    part.dtype)], axis=0)
            return jnp.asarray(part)

        idx = jnp.asarray(chunk + [0] * pad, jnp.int32)
        kv_cache = _paged_store_jit(
            kv_cache, jax.tree.map(_slice, payload), idx)
    return kv_cache


# --------------------------------------------------------------------
# Ring-attention prefill offload (ISSUE 13): prompts beyond one
# device's prefill budget run their prompt pass SEQUENCE-PARALLEL over
# the training tier's causal ring attention (parallel/ring_attention,
# striped layout for ring balance) and land the resulting per-layer
# K/V straight into KV pages, so single-device paged decode proceeds
# normally afterward. The harvest rides a mutable 'ring_kv' collection
# each CausalAttention layer sows its post-rotary K/V into (KV-head
# granularity — exactly the tensors the page store holds); right-pad
# tokens are harmless under the causal mask and their landed garbage
# is overwritten by decode before any read can see it.


@_lru("ring_prefill", maxsize=16)
def _compiled_ring_prefill(sm, b: int, s: int, n: int, layout: str):
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from tpuflow.core.compat import shard_map
    from tpuflow.parallel.ring_attention import ring_prefill_layout

    if s % n:
        raise ValueError(
            f"padded prompt length {s} must divide by the ring size "
            f"{n} (pad to the pow2 bucket)")
    mesh = Mesh(np.array(jax.devices()[:n]), ("ringpf",))
    perm, inv = ring_prefill_layout(s, n, layout)
    permj = None if perm is None else jnp.asarray(perm)
    invj = None if inv is None else jnp.asarray(inv)

    def shard_fwd(params, toks):
        _, vars2 = sm.apply({"params": params}, toks,
                            mutable=["ring_kv"])
        return vars2["ring_kv"]

    smapped = shard_map(
        shard_fwd, mesh=mesh,
        in_specs=(P(), P(None, "ringpf")),
        out_specs=P(None, None, "ringpf", None),
    )

    @_rjit(key="infer.ring_prefill")
    def run(params, tokens):
        if permj is not None:
            tokens = tokens[:, permj]
        kv = smapped(params, tokens)

        def unstripe(leaf):  # back to logical token order (seq axis 2)
            return leaf if invj is None else leaf[:, :, invj, :]

        return jax.tree.map(unstripe, kv)

    return run


def ring_prefill_kv(model, params, tokens, *, n_shards: int,
                    layout: str = "striped"):
    """Sequence-parallel prompt prefill: run ``tokens`` (B=1, S with
    ``S % n_shards == 0``) through the model's ring-attention form
    over ``n_shards`` devices and return the ``ring_kv`` collection —
    per layer, post-rotary K/V ``(B, KVH, S, D)`` tuples in LOGICAL
    token order, the exact values a single-device prefill writes into
    the KV cache (up to ring-merge rounding). Per-device residency is
    O(S / n_shards). ``layout='striped'`` (default) balances the
    causal ring's wall time (~n/2 visits instead of ~n). Feed the
    result to :meth:`tpuflow.serve.pages.PagedKV.land_ring`."""
    sm = model.clone(decode=False, seq_axis="ringpf", sp_layout=layout,
                     skip_head=True)
    b, s = tokens.shape
    run = _compiled_ring_prefill(sm, int(b), int(s), int(n_shards),
                                 str(layout))
    with trace.span("infer.ring_prefill", phase="prefill", rows=b,
                    tokens=s, n_shards=n_shards, layout=layout):
        return run(params, jnp.asarray(tokens, jnp.int32))


@_rjit(key="infer.paged_land", donate_argnums=(0,))
def _paged_land_jit(cache, harvest, pages):
    # pages: (n_row_pages,) physical page of each landed row-page slot,
    # 0 (the write sink) past the landed chain — duplicate sink writes
    # scribble garbage nobody reads, which is what keeps the scatter
    # ONE fixed-shape executable per pool instead of one per prompt
    # length. Donated store: the landing is in place (ISSUE 11's
    # contract — the caller reassigns from the return value).
    def walk(cnode, hnode):
        out = {}
        for name, leaf in cnode.items():
            if name in ("key_pages", "value_pages"):
                src = hnode["k" if name == "key_pages" else "v"]
                if isinstance(src, (tuple, list)):  # flax sow tuple
                    src = src[0]
                n = pages.shape[0]
                kvh, ps, d = leaf.shape[1], leaf.shape[2], leaf.shape[3]
                s = src.shape[2]
                content = src[0]  # (KVH, S, D)
                if n * ps > s:
                    content = jnp.pad(
                        content, ((0, 0), (0, n * ps - s), (0, 0)))
                content = content[:, : n * ps].reshape(
                    kvh, n, ps, d).transpose(1, 0, 2, 3)
                out[name] = leaf.at[pages].set(
                    content.astype(leaf.dtype))
            elif isinstance(leaf, dict):
                out[name] = walk(leaf, hnode[name])
            else:  # int8 scale leaves never reach this path (gated)
                out[name] = leaf
        return out

    return walk(dict(cache), dict(harvest))


def paged_land(kv_cache, harvest, pages):
    """Scatter a :func:`ring_prefill_kv` harvest into the page store:
    row-page slot j of ``pages`` receives the harvest's positions
    ``[j*ps, (j+1)*ps)``. See ``PagedKV.land_ring`` for the policy
    half (which pages, how many, the sink-tail contract)."""
    import numpy as np

    return _paged_land_jit(kv_cache, harvest,
                           jnp.asarray(np.asarray(pages, np.int32)))


def serve_join_fn(model, slots: int, length: int, bucket: int):
    """Compiled per-slot prefill: admit requests into freed slots of a
    live pool at boundary ``t0``.

    Returns ``join(params, cache, out, pad_lens, prompts, join_mask,
    t0) -> (cache, out)`` where ``prompts`` is (slots, bucket) int32
    rows LEFT-padded to the bucket (only rows with ``join_mask`` True
    are read), ``pad_lens`` is the POST-join (slots,) pad vector
    (pad_lens[r] = t0 - prompt_len_r + 1 for joining rows, unchanged
    for the rest), and ``t0`` is the boundary step index — the joining
    prompt's last token lands at buffer position t0, so the next decode
    step treats joined and in-flight rows identically. The prefill
    pass covers window [t0-bucket+1, t0) (the last prompt token's KV is
    appended by that next step); its cache rows merge in ONLY where
    ``join_mask`` is set."""
    if bucket < 2:
        raise ValueError(f"bucket must be >= 2, got {bucket}")
    if length < bucket:
        raise ValueError(f"length ({length}) must be >= bucket ({bucket})")
    dm = model.clone(decode=True, seq_axis=None)
    return _compiled_serve_join(dm, int(slots), int(length), int(bucket))


@_lru("serve_join", maxsize=32)
def _compiled_serve_join(dm, b: int, length: int, bucket: int):
    @_rjit(key="infer.serve_join")
    def join(params, cache, out, pad_lens, prompts, join_mask, t0):
        start = t0 - bucket + 1
        out_new = lax.dynamic_update_slice(out, prompts, (0, start))
        out = jnp.where(join_mask[:, None], out_new, out)
        # prefill the window ENDING at t0 (exclusive): bucket-1 tokens,
        # so the next decode step appends the last prompt token's KV at
        # t0 for joined rows exactly as it does for in-flight rows
        chunk = lax.dynamic_slice(out, (0, start), (b, bucket - 1))
        _, vars2 = dm.apply(
            {"params": params, "cache": _set_cache_index(cache, start)},
            chunk, mutable=["cache"], pad_lens=pad_lens,
        )
        # per-slot cache write: joining rows take the prefilled KV,
        # in-flight rows keep their live state (the scalar index leaves
        # agree: start + (bucket-1) == t0 == the live index)
        cache = _merge_rows(vars2["cache"], cache, join_mask)
        return cache, out

    return join


def serve_segment_fn(model, slots: int, length: int, seg: int,
                     temperature: float, top_k: Optional[int],
                     top_p: Optional[float], eos_id: Optional[int]):
    """Compiled decode segment: advance a pool ``seg`` steps from
    boundary ``t0``, then return control to the host.

    Returns ``segment(params, cache, out, done, pad_lens, stream_ids,
    last_pos, rng, t0) -> (cache, out, done, toks)``:

    - ``done`` (slots,) bool: finished/empty rows keep stepping (fixed
      shapes) but write ``eos_id`` (or 0) and never un-finish;
    - ``stream_ids`` (slots,) int32: the per-REQUEST sampling stream id
      (replaces the physical row in ``_sample``'s key derivation);
    - ``last_pos`` (slots,) int32: the row's final allowed buffer
      position (join boundary + its max_new_tokens) — writing it sets
      ``done`` (per-request token budgets at slot granularity);
    - ``toks``: the (slots, seg) block written this segment (buffer
      positions [t0+1, t0+seg]) — the host streams per-request slices
      of it at every boundary.

    The caller aligns segments to the grid (t0 = bucket-1 + k*seg and
    t0 + seg <= length-1): ``lax.dynamic_update_slice`` CLAMPS
    out-of-range starts, so an unaligned tail segment would silently
    rewrite position length-1."""
    dm = model.clone(decode=True, seq_axis=None)
    return _compiled_serve_segment(
        dm, int(slots), int(length), int(seg), float(temperature),
        None if top_k is None else int(top_k),
        None if top_p is None else float(top_p),
        None if eos_id is None else int(eos_id),
    )


@_lru("serve_segment", maxsize=32)
def _compiled_serve_segment(dm, b: int, length: int, seg: int,
                            temperature: float, top_k: Optional[int],
                            top_p: Optional[float],
                            eos_id: Optional[int]):
    fill = jnp.int32(eos_id if eos_id is not None else 0)

    @_rjit(key="infer.serve_segment")
    def segment(params, cache, out, done, pad_lens, stream_ids,
                last_pos, rng, t0):
        def step(carry, i):
            cache, out, done = carry
            t = t0 + i
            tok = lax.dynamic_slice(out, (0, t), (b, 1))
            lg, vars2 = dm.apply(
                {"params": params, "cache": cache}, tok,
                mutable=["cache"], pad_lens=pad_lens,
            )
            nxt = _sample(lg[:, -1], rng, temperature, top_k, top_p,
                          step=t - pad_lens, row_ids=stream_ids)
            nxt = jnp.where(done, fill, nxt)
            done = done | (t + 1 >= last_pos)
            if eos_id is not None:
                done = done | (nxt == eos_id)
            out = lax.dynamic_update_slice(out, nxt[:, None], (0, t + 1))
            return (vars2["cache"], out, done), None

        (cache, out, done), _ = lax.scan(
            step, (cache, out, done), jnp.arange(seg)
        )
        toks = lax.dynamic_slice(out, (0, t0 + 1), (b, seg))
        return cache, out, done, toks

    return segment


@_lru("stepwise", maxsize=64)
def _compiled_run(dm, b: int, p: int, max_len: int, temperature: float,
                  top_k: Optional[int], top_p: Optional[float],
                  eos_id: Optional[int]):
    """The stepwise prompt+decode scan (the original engine), memoized
    on (model, shapes, sampling config). ONE scan of ``max_len - 1``
    single-token steps covers prefill and sampling; kept as the parity
    oracle for the blockwise engine and as the conservative fallback."""

    @_rjit(key="infer.stepwise")
    def run(params, prompt, rng):
        cache0 = _cache_zeros(dm, b, max_len)
        out0 = jnp.zeros((b, max_len), jnp.int32)
        out0 = lax.dynamic_update_slice(out0, prompt, (0, 0))
        done0 = jnp.zeros((b,), jnp.bool_)

        def step(carry, t):
            cache, out, done = carry
            tok = lax.dynamic_slice(out, (0, t), (b, 1))
            logits, vars2 = dm.apply(
                {"params": params, "cache": cache}, tok, mutable=["cache"]
            )
            nxt = _sample(
                logits[:, -1], jax.random.fold_in(rng, t), temperature,
                top_k, top_p,
            )
            # positions < p-1 are prefill: keep the prompt token that is
            # already in ``out`` instead of the model's prediction
            gen_pos = t + 1 >= p
            cur = lax.dynamic_slice(out, (0, t + 1), (b, 1))[:, 0]
            nxt = jnp.where(gen_pos, nxt, cur)
            if eos_id is not None:  # only GENERATED eos stops a row
                nxt = jnp.where(gen_pos & done, jnp.int32(eos_id), nxt)
                done = done | (gen_pos & (nxt == eos_id))
            out = lax.dynamic_update_slice(out, nxt[:, None], (0, t + 1))
            return (vars2["cache"], out, done), None

        (cache, out, _), _ = lax.scan(
            step, (cache0, out0, done0), jnp.arange(max_len - 1)
        )
        return out

    return run
