"""Autoregressive text generation with a KV cache.

The reference has no generative model at all (its inference path is
image classification via a packaged pyfunc, P2/03); this rounds out the
transformer-LM family (tpuflow.models.transformer) with the standard
serving loop, TPU-idiomatically:

- one jitted ``lax.scan`` covers prefill AND sampling — static trip
  count (``max_len``), static shapes throughout, single compilation;
- the KV cache is a flax ``cache`` collection created at trace time
  with the full target length (decode steps ``dynamic_update_slice``
  into it), so XLA sees one fixed buffer per layer — no growing
  tensors, no host round-trips per token;
- sampling is temperature + optional top-k and nucleus (top-p)
  filtering over float32 logits with a counter-derived ``jax.random``
  key per step.

Greedy (temperature=0) decode is exact argmax; the cache-consistency
property (stepwise logits == full-forward logits) is tested in
tests/test_generate.py.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax


def _sample(logits, rng, temperature: float, top_k: Optional[int],
            top_p: Optional[float] = None):
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    want_p = top_p is not None and top_p < 1.0
    if top_k is not None or want_p:
        # ONE descending sort serves both filters, and the keep mask is
        # scattered back by INDEX — a value threshold would keep every
        # token tied with the cutoff logit (uniform logits + top_p=0.5
        # would filter nothing)
        vocab = logits.shape[-1]
        idx = jnp.argsort(logits, axis=-1)[..., ::-1]
        desc = jnp.take_along_axis(logits, idx, axis=-1)
        keep_sorted = jnp.ones(desc.shape, bool)
        if top_k is not None:
            k = min(max(int(top_k), 1), vocab)
            keep_sorted &= jnp.arange(vocab) < k
        if want_p:
            # nucleus: the smallest prefix of descending-prob tokens
            # whose mass reaches top_p (the top token always stays —
            # its preceding cumulative mass is 0)
            probs = jax.nn.softmax(desc, axis=-1)
            before = jnp.cumsum(probs, axis=-1) - probs
            keep_sorted &= before < top_p
        keep = jnp.zeros(desc.shape, bool)
        keep = jnp.put_along_axis(keep, idx, keep_sorted, axis=-1,
                                  inplace=False)
        logits = jnp.where(keep, logits, -1e30)
    # per-ROW keys (fold_in by row index): row i's RANDOMNESS depends
    # only on (seed, step, i), never on the batch SHAPE — so a prompt's
    # sampled continuation no longer varies with pad-row count through
    # the RNG (packaging/lm.py pads length-buckets with copies of row
    # 0; a single batch-shaped categorical draw would give different
    # outputs for the same prompt+seed depending on the pad count).
    # Caveat: the LOGITS themselves are only batch-shape-invariant up
    # to the backend's reduction order — an ulp-level logit difference
    # near a probability boundary can still flip a draw on some
    # backends; the guarantee here is RNG invariance, not bitwise
    # forward-pass invariance
    keys = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
        jnp.arange(logits.shape[0])
    )
    return jax.vmap(
        lambda lg, k: jax.random.categorical(k, lg)
    )(logits, keys).astype(jnp.int32)


def generate(
    model,
    params,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    seed: int = 0,
    eos_id: Optional[int] = None,
) -> jnp.ndarray:
    """Generate continuations for a batch of prompts.

    ``model``: a TransformerLM built with ``decode=False`` (its decode
    twin is derived here via ``.clone(decode=True)``); ``params``: its
    (unboxed) params. ``prompt``: (B, P) int32. Returns (B, P +
    max_new_tokens) int32 — prompts with sampled continuations; after a
    row emits ``eos_id`` its remaining positions repeat ``eos_id``.

    The whole prompt+generate loop is ONE jitted scan of
    ``P + max_new_tokens - 1`` single-token steps against a
    fixed-length KV cache. (A blockwise prefill is a future
    optimization; generation cost is dominated by the sampling steps.)
    """
    dm = model.clone(decode=True, seq_axis=None)
    b, p = prompt.shape
    if p < 1:
        raise ValueError("prompt must have at least one token")
    if top_k is not None:
        vocab = getattr(model, "vocab_size", None)
        if top_k < 1 or (vocab is not None and top_k > vocab):
            raise ValueError(
                f"top_k={top_k} must be in [1, vocab_size"
                f"{'=' + str(vocab) if vocab is not None else ''}]"
            )
    if top_p is not None and not (0.0 < top_p <= 1.0):
        raise ValueError(f"top_p={top_p} must be in (0, 1]")
    max_len = p + max_new_tokens
    run = _compiled_run(dm, b, p, max_len, float(temperature),
                        None if top_k is None else int(top_k),
                        None if top_p is None else float(top_p), eos_id)
    return run(params, jnp.asarray(prompt, jnp.int32),
               jax.random.key(seed))


def clear_compile_cache() -> None:
    """Drop all memoized jitted decode closures (each holds a compiled
    executable and a model reference). A long-lived server cycling many
    distinct prompt shapes / sampling configs can call this to bound
    resident compile-cache growth; bucketing prompt lengths before
    calling :func:`generate` keeps the cache small in the first place
    (ADVICE r2)."""
    _compiled_run.cache_clear()


@functools.lru_cache(maxsize=64)
def _compiled_run(dm, b: int, p: int, max_len: int, temperature: float,
                  top_k: Optional[int], top_p: Optional[float],
                  eos_id: Optional[int]):
    """The jitted prompt+decode scan, memoized on (model, shapes,
    sampling config) — a serving loop calling generate() per request
    with identical shapes must compile ONCE, not per call (flax modules
    are frozen dataclasses, so ``dm`` is a valid cache key). Bounded at
    64 entries; :func:`clear_compile_cache` empties it on demand."""

    # cache struct at full length via eval_shape (no FLOPs), then zeros
    cache_shapes = jax.eval_shape(
        lambda: dm.init(
            {"params": jax.random.key(0)},
            jnp.zeros((b, max_len), jnp.int32),
        )["cache"]
    )
    @jax.jit
    def run(params, prompt, rng):
        # zeros built INSIDE the jit: the memoized closure then holds
        # only ShapeDtypeStructs, not live device buffers
        cache0 = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes
        )
        out0 = jnp.zeros((b, max_len), jnp.int32)
        out0 = lax.dynamic_update_slice(out0, prompt, (0, 0))
        done0 = jnp.zeros((b,), jnp.bool_)

        def step(carry, t):
            cache, out, done = carry
            tok = lax.dynamic_slice(out, (0, t), (b, 1))
            logits, vars2 = dm.apply(
                {"params": params, "cache": cache}, tok, mutable=["cache"]
            )
            nxt = _sample(
                logits[:, -1], jax.random.fold_in(rng, t), temperature,
                top_k, top_p,
            )
            # positions < p-1 are prefill: keep the prompt token that is
            # already in ``out`` instead of the model's prediction
            gen_pos = t + 1 >= p
            cur = lax.dynamic_slice(out, (0, t + 1), (b, 1))[:, 0]
            nxt = jnp.where(gen_pos, nxt, cur)
            if eos_id is not None:  # only GENERATED eos stops a row
                nxt = jnp.where(gen_pos & done, jnp.int32(eos_id), nxt)
                done = done | (gen_pos & (nxt == eos_id))
            out = lax.dynamic_update_slice(out, nxt[:, None], (0, t + 1))
            return (vars2["cache"], out, done), None

        (cache, out, _), _ = lax.scan(
            step, (cache0, out0, done0), jnp.arange(max_len - 1)
        )
        return out

    return run
