"""Distributed batch inference (C16) — the spark_udf equivalent.

≙ ``mlflow.pyfunc.spark_udf(spark, model_uri, result_type='string')``
applied to a table's ``content`` column (P2/03_pyfunc_distributed_
inference.py:466-472): each executor loads the packaged model once and
maps it over its partitions. TPU-native form: each PROCESS loads the
model once and STREAMS its row shard through the jitted forward on its
local devices; results land in a predictions table (one part per
shard), so the multi-host path needs no driver gather.

The read path is streaming: record batches are pulled one at a time
from the Parquet files (never ``table.read()``), the shard mask and
``limit`` are applied per batch BEFORE any Python materialization, and
sharded rows are buffered up to ``batch_size`` so every jitted forward
(except the final remainder) runs a FULL batch — no padding waste from
shard-thinned or row-group-truncated record batches. In
``output_table`` mode host memory is bounded by ``batch_size`` +
``flush_rows`` regardless of table size — the property the reference
gets from Spark's per-partition UDF execution. (The return-a-table
mode necessarily holds the shard's result in memory; use
``output_table`` for beyond-memory tables.)
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import pyarrow as pa

from tpuflow.data.loader import take_shard_rows
from tpuflow.data.table import Table
from tpuflow.packaging.model import PackagedModel, load_packaged_model


def predict_table(
    model: "PackagedModel | str",
    table: Table,
    content_col: str = "content",
    batch_size: int = 64,
    shard: Tuple[int, int] = (0, 1),
    limit: Optional[int] = None,
    output_table: Optional[Table] = None,
    store=None,
    registry=None,
    flush_rows: int = 4096,
) -> Optional[pa.Table]:
    """Map a packaged model over one shard of ``table``, streaming.

    Returns the shard's rows with a ``prediction`` string column
    appended (≙ df.withColumn('prediction', udf('content')),
    P2/03:468-472). ``limit`` mirrors the notebook's ``limit(1000)``
    smoke runs (P2/03:470) and counts GLOBAL (pre-shard) rows. With
    ``output_table``, prediction chunks are appended there in
    ``flush_rows``-sized commits instead of being accumulated, and the
    return value is ``None`` — the bounded-memory multi-host pattern
    (every process writes its own shard; shard (i,n) rows are disjoint
    by construction).
    """
    if isinstance(model, str):
        model = load_packaged_model(model, store=store, registry=registry)

    chunks: List[pa.Table] = []  # return path only
    out_pending: List[pa.Table] = []  # output_table path only
    out_pending_rows = 0
    ensured = False

    def flush_out() -> None:
        nonlocal out_pending, out_pending_rows, ensured
        if not out_pending:
            return
        out = pa.concat_tables(out_pending)
        # ensure-then-append (not exists?-overwrite:-append) so two
        # processes' first flushes can't both pick "overwrite" and one
        # clobber the other's committed rows; latched after the first
        # flush — the table is guaranteed to exist from then on
        if not ensured:
            output_table.ensure(out.schema)
            ensured = True
        output_table.write(out, mode="append")
        out_pending, out_pending_rows = [], 0

    def deliver(chunk: pa.Table) -> None:
        nonlocal out_pending_rows
        if output_table is not None:
            out_pending.append(chunk)
            out_pending_rows += chunk.num_rows
            if out_pending_rows >= flush_rows:
                flush_out()
        else:
            chunks.append(chunk)

    # shard-thinned rows buffered until a full model batch is ready
    ready: List[pa.Table] = []
    n_ready = 0

    def predict_ready(final: bool = False) -> None:
        nonlocal ready, n_ready
        take = n_ready if final else (n_ready // batch_size) * batch_size
        if take == 0:
            return
        allt = pa.concat_tables(ready)
        head, rest = allt.slice(0, take), allt.slice(take)
        # by-name lookup raises KeyError on a missing/misspelled column
        preds = model.predict(
            head.column(content_col).to_pylist(), batch_size
        )
        deliver(
            head.append_column("prediction", pa.array(preds, pa.string()))
        )
        ready = [rest] if rest.num_rows else []
        n_ready = rest.num_rows

    gidx = 0
    for rb in table.iter_batches(batch_size=batch_size):
        if limit is not None and gidx >= limit:
            break
        if limit is not None and gidx + rb.num_rows > limit:
            rb = rb.slice(0, limit - gidx)
        # shard by global row index — the same take_shard_rows
        # assignment the training loader uses, applied per streamed batch
        sub = take_shard_rows(rb, gidx, shard)
        gidx += rb.num_rows
        if sub is not None and sub.num_rows:
            ready.append(pa.Table.from_batches([sub]))
            n_ready += sub.num_rows
            predict_ready()
    predict_ready(final=True)

    if output_table is not None:
        flush_out()
        # empty shard: still create the table (0 rows, full schema) so
        # readers never race a missing _latest; ensure() is atomic and
        # never clobbers rows a sibling shard already appended
        if not ensured:
            output_table.ensure(
                table.schema().append(pa.field("prediction", pa.string()))
            )
        return None
    if not chunks:
        schema = table.schema().append(pa.field("prediction", pa.string()))
        return schema.empty_table()
    return pa.concat_tables(chunks)
