"""Distributed batch inference (C16) — the spark_udf equivalent.

≙ ``mlflow.pyfunc.spark_udf(spark, model_uri, result_type='string')``
applied to a table's ``content`` column (P2/03_pyfunc_distributed_
inference.py:466-472): each executor loads the packaged model once and
maps it over its partitions. TPU-native form: each PROCESS loads the
model once and STREAMS its row shard through the jitted forward on its
local devices; results land in a predictions table (one part per
shard), so the multi-host path needs no driver gather.

The read path is streaming: record batches are pulled one at a time
from the Parquet files (never ``table.read()``), the shard mask and
``limit`` are applied per batch BEFORE any Python materialization, and
sharded rows are buffered up to ``batch_size`` so every jitted forward
(except the final remainder) runs a FULL batch — no padding waste from
shard-thinned or row-group-truncated record batches. In
``output_table`` mode host memory is bounded by ``batch_size`` +
``flush_rows`` regardless of table size — the property the reference
gets from Spark's per-partition UDF execution. (The return-a-table
mode necessarily holds the shard's result in memory; use
``output_table`` for beyond-memory tables.)

Two frontends share the machinery: :func:`predict_table` maps the
image classifier's packaged model (bytes → class-name strings), and
:func:`generate_table` maps a packaged LM's text surface (prompt
strings → continuations) — the LM family's C16, which the reference
cannot express at all (its only inference is image classification).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import pyarrow as pa

from tpuflow.data.loader import take_shard_rows
from tpuflow.data.table import Table


def _map_table_shard(
    map_fn: Callable[[Sequence], List[str]],
    out_field: pa.Field,
    table: Table,
    content_col: str,
    batch_size: int,
    shard: Tuple[int, int],
    limit: Optional[int],
    output_table: Optional[Table],
    flush_rows: int,
) -> Optional[pa.Table]:
    """Stream one shard of ``table`` through ``map_fn`` (a list of
    ``content_col`` values in, one output string per row out), appending
    the results as ``out_field``. The shared engine behind
    predict_table/generate_table — sharding, full-batch buffering,
    limit, and the bounded-memory output_table protocol live here
    exactly once."""
    chunks: List[pa.Table] = []  # return path only
    out_pending: List[pa.Table] = []  # output_table path only
    out_pending_rows = 0
    ensured = False

    def flush_out() -> None:
        nonlocal out_pending, out_pending_rows, ensured
        if not out_pending:
            return
        out = pa.concat_tables(out_pending)
        # ensure-then-append (not exists?-overwrite:-append) so two
        # processes' first flushes can't both pick "overwrite" and one
        # clobber the other's committed rows; latched after the first
        # flush — the table is guaranteed to exist from then on
        if not ensured:
            output_table.ensure(out.schema)
            ensured = True
        output_table.write(out, mode="append")
        out_pending, out_pending_rows = [], 0

    def deliver(chunk: pa.Table) -> None:
        nonlocal out_pending_rows
        if output_table is not None:
            out_pending.append(chunk)
            out_pending_rows += chunk.num_rows
            if out_pending_rows >= flush_rows:
                flush_out()
        else:
            chunks.append(chunk)

    # shard-thinned rows buffered until a full model batch is ready
    ready: List[pa.Table] = []
    n_ready = 0

    def predict_ready(final: bool = False) -> None:
        nonlocal ready, n_ready
        take = n_ready if final else (n_ready // batch_size) * batch_size
        if take == 0:
            return
        allt = pa.concat_tables(ready)
        head, rest = allt.slice(0, take), allt.slice(take)
        # by-name lookup raises KeyError on a missing/misspelled column
        outs = map_fn(head.column(content_col).to_pylist())
        deliver(
            head.append_column(out_field, pa.array(outs, out_field.type))
        )
        ready = [rest] if rest.num_rows else []
        n_ready = rest.num_rows

    gidx = 0
    for rb in table.iter_batches(batch_size=batch_size):
        if limit is not None and gidx >= limit:
            break
        if limit is not None and gidx + rb.num_rows > limit:
            rb = rb.slice(0, limit - gidx)
        # shard by global row index — the same take_shard_rows
        # assignment the training loader uses, applied per streamed batch
        sub = take_shard_rows(rb, gidx, shard)
        gidx += rb.num_rows
        if sub is not None and sub.num_rows:
            ready.append(pa.Table.from_batches([sub]))
            n_ready += sub.num_rows
            predict_ready()
    predict_ready(final=True)

    if output_table is not None:
        flush_out()
        # empty shard: still create the table (0 rows, full schema) so
        # readers never race a missing _latest; ensure() is atomic and
        # never clobbers rows a sibling shard already appended
        if not ensured:
            output_table.ensure(table.schema().append(out_field))
        return None
    if not chunks:
        return table.schema().append(out_field).empty_table()
    return pa.concat_tables(chunks)


def predict_table(
    model,
    table: Table,
    content_col: str = "content",
    batch_size: int = 64,
    shard: Tuple[int, int] = (0, 1),
    limit: Optional[int] = None,
    output_table: Optional[Table] = None,
    store=None,
    registry=None,
    flush_rows: int = 4096,
) -> Optional[pa.Table]:
    """Map a packaged image model over one shard of ``table``, streaming.

    Returns the shard's rows with a ``prediction`` string column
    appended (≙ df.withColumn('prediction', udf('content')),
    P2/03:468-472). ``limit`` mirrors the notebook's ``limit(1000)``
    smoke runs (P2/03:470) and counts GLOBAL (pre-shard) rows. With
    ``output_table``, prediction chunks are appended there in
    ``flush_rows``-sized commits instead of being accumulated, and the
    return value is ``None`` — the bounded-memory multi-host pattern
    (every process writes its own shard; shard (i,n) rows are disjoint
    by construction).
    """
    from tpuflow.packaging.model import load_packaged_model

    if isinstance(model, str):
        model = load_packaged_model(model, store=store, registry=registry)
    return _map_table_shard(
        lambda vals: model.predict(vals, batch_size),
        pa.field("prediction", pa.string()),
        table, content_col, batch_size, shard, limit, output_table,
        flush_rows,
    )


def generate_table(
    model,
    table: Table,
    text_col: str = "text",
    batch_size: int = 16,
    shard: Tuple[int, int] = (0, 1),
    limit: Optional[int] = None,
    output_table: Optional[Table] = None,
    store=None,
    registry=None,
    flush_rows: int = 4096,
    max_new_tokens: Optional[int] = None,
    serve_slots: Optional[int] = None,
    scheduler: str = "slot",
    **generate_kwargs,
) -> Optional[pa.Table]:
    """Map a packaged LM's TEXT surface over one shard of ``table``:
    each row of ``text_col`` (a prompt string) gains a ``generation``
    string column holding prompt + continuation (generate_text's
    contract — the prompt is INCLUDED, strip it by prefix length if
    only the new text is wanted) — the LM-family C16, same
    sharding/streaming/output_table semantics as :func:`predict_table`
    (shard (i, n) rows are disjoint, so every process writes its own
    part).

    Rows inside each engine batch are served BUCKETED: prompts group
    into power-of-two token-length buckets, left-padded with the pad
    slots attention-masked, so the blockwise prefill + early-exit
    decode engine (tpuflow.infer.generate) compiles once per (length
    bucket, batch bucket) instead of once per distinct prompt length,
    and with ``serve_slots`` set each bucket is served at SLOT
    granularity by default (``scheduler='slot'`` — the tpuflow.serve
    continuous-batching runtime: finished rows free their slot at
    decode-segment boundaries and queued prompts prefill into them
    mid-flight; token-identical to wave draining under pinned seeds).
    ``scheduler='wave'`` keeps the original wave-drain loop — required
    when passing engine-tuning kwargs (engine, prefill_chunk,
    decode_segment), which the slot route rejects. ``model`` is a
    PackagedLM, a path, or a ``runs:/`` / ``models:/`` URI; sampling
    kwargs (temperature, top_k, top_p, seed, eos_id) default to the
    packaged ``generate_defaults``.
    """
    from tpuflow.packaging.lm import PackagedLM, load_packaged_lm

    if isinstance(model, str):
        model = load_packaged_lm(model, store=store, registry=registry)
    if not isinstance(model, PackagedLM):
        raise TypeError(
            f"generate_table needs a PackagedLM (or a path/URI to one), "
            f"got {type(model).__name__}"
        )
    return _map_table_shard(
        lambda texts: model.generate_text(
            texts, max_new_tokens=max_new_tokens, serve_slots=serve_slots,
            scheduler=scheduler, **generate_kwargs
        ),
        pa.field("generation", pa.string()),
        table, text_col, batch_size, shard, limit, output_table,
        flush_rows,
    )
