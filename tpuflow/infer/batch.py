"""Distributed batch inference (C16) — the spark_udf equivalent.

≙ ``mlflow.pyfunc.spark_udf(spark, model_uri, result_type='string')``
applied to a table's ``content`` column (P2/03_pyfunc_distributed_
inference.py:466-472): each executor loads the packaged model once and
maps it over its partitions. TPU-native form: each PROCESS loads the
model once and streams its row shard through the jitted forward on its
local devices; results land in a predictions table (one part per
shard), so the multi-host path needs no driver gather.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Tuple

import pyarrow as pa

from tpuflow.data.table import Table
from tpuflow.packaging.model import PackagedModel, load_packaged_model


def predict_table(
    model: "PackagedModel | str",
    table: Table,
    content_col: str = "content",
    batch_size: int = 64,
    shard: Tuple[int, int] = (0, 1),
    limit: Optional[int] = None,
    output_table: Optional[Table] = None,
    store=None,
    registry=None,
) -> pa.Table:
    """Map a packaged model over one shard of ``table``.

    Returns the shard's rows with a ``prediction`` string column
    appended (≙ df.withColumn('prediction', udf('content')),
    P2/03:468-472). ``limit`` mirrors the notebook's ``limit(1000)``
    smoke runs (P2/03:470). With ``output_table``, predictions are
    appended there instead (multi-host pattern: every process writes
    its own shard, shard (i,n) rows are disjoint by construction).
    """
    if isinstance(model, str):
        model = load_packaged_model(model, store=store, registry=registry)
    cur, n_shards = shard
    data = table.read()
    if limit is not None:
        data = data.slice(0, limit)
    if n_shards > 1:
        import numpy as np

        idx = np.arange(data.num_rows)
        data = data.take(pa.array(idx[idx % n_shards == cur]))
    preds: List[str] = []
    contents = data.column(content_col).to_pylist()
    for s in range(0, len(contents), batch_size):
        preds.extend(model.predict(contents[s : s + batch_size], batch_size))
    out = data.append_column("prediction", pa.array(preds, pa.string()))
    if output_table is not None:
        output_table.write(out, mode="append" if output_table.exists() else "overwrite")
    return out
