"""tpuflow benchmark: images/sec/chip on the flagship DP training config.

Measures the steady-state jitted train step of the MobileNetV2 transfer
classifier (the reference's distributed config: 224x224x3, per-worker
batch 256 — P1/03_model_training_distributed.py:81) on all local
devices, and reports ONE JSON line:

  {"metric": "train_images_per_sec_per_chip", "value": N,
   "unit": "images/s/chip", "vs_baseline": R}

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is
anchored to the driver's north star instead: measured MFU / 0.60 (the
"≥60% MFU" target from BASELINE.json) — 1.0 means the target is met.
FLOPs come from XLA cost analysis of the compiled step (obs.mfu).

Extra diagnostics (stderr): MFU, step time, native-decode throughput.
Usage: python bench.py [--smoke] [--batch N] [--steps N]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true",
                   help="tiny shapes on CPU (CI smoke)")
    p.add_argument("--batch", type=int, default=None, help="per-chip batch")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--warmup", type=int, default=5)
    args = p.parse_args()

    if args.smoke:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tpuflow.core.config import TrainConfig
    from tpuflow.models import build_model
    from tpuflow.obs.mfu import device_peak_flops, flops_of_jitted
    from tpuflow.parallel.mesh import MeshSpec, build_mesh
    from tpuflow.train import Trainer

    devices = jax.devices()
    n_chips = len(devices)
    if args.smoke:
        hw, width, batch = 64, 0.25, args.batch or 8
    else:
        # the reference's distributed per-worker batch (P1/03:81)
        hw, width, batch = 224, 1.0, args.batch or 256
    global_batch = batch * n_chips

    mesh = build_mesh(MeshSpec(data=n_chips, model=1))
    model = build_model(num_classes=5, dropout=0.5, width_mult=width)
    trainer = Trainer(model, TrainConfig(learning_rate=1e-3, warmup_epochs=0),
                      mesh=mesh)
    trainer.init_state((hw, hw, 3))
    trainer._make_steps()

    rng = np.random.default_rng(0)
    batch_np = {
        "image": rng.integers(0, 255, (global_batch, hw, hw, 3)).astype(np.uint8),
        "label": rng.integers(0, 5, (global_batch,)).astype(np.int32),
    }
    images, labels = trainer._put(batch_np)
    lr = jnp.asarray(1e-3, jnp.float32)

    t_compile = time.time()
    state, m = trainer._train_step(trainer.state, images, labels, lr)
    jax.block_until_ready(m)
    compile_s = time.time() - t_compile

    flops = flops_of_jitted(
        trainer._train_step, trainer.state, images, labels, lr
    )

    for _ in range(args.warmup):
        state, m = trainer._train_step(state, images, labels, lr)
    jax.block_until_ready(m)
    t0 = time.time()
    for _ in range(args.steps):
        state, m = trainer._train_step(state, images, labels, lr)
    jax.block_until_ready(m)
    dt = (time.time() - t0) / args.steps

    img_per_sec_chip = global_batch / dt / n_chips
    peak = device_peak_flops(devices[0])
    mfu_val = (flops / dt) / (n_chips * peak) if flops else 0.0

    # decode-plane diagnostic (not part of the headline number)
    decode_rate = _decode_diag(hw)

    print(
        f"# devices={n_chips} ({devices[0].device_kind}) hw={hw} width={width} "
        f"batch/chip={batch} step={dt*1e3:.2f}ms compile={compile_s:.1f}s "
        f"flops/step={flops:.3e} MFU={mfu_val*100:.1f}% "
        f"decode={decode_rate:.0f} img/s loss={float(m['loss']):.4f}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "train_images_per_sec_per_chip",
                "value": round(img_per_sec_chip, 2),
                "unit": "images/s/chip",
                "vs_baseline": round(mfu_val / 0.60, 4),
            }
        )
    )
    return 0


def _decode_diag(hw: int) -> float:
    try:
        import io

        import numpy as np
        from PIL import Image

        from tpuflow.native import decode_resize_batch

        arr = (np.random.default_rng(0).random((256, 256, 3)) * 255).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=90)
        jpegs = [buf.getvalue()] * 128
        decode_resize_batch(jpegs[:8], hw, hw)  # warm
        t0 = time.time()
        decode_resize_batch(jpegs, hw, hw, num_threads=os.cpu_count() or 1)
        return len(jpegs) / (time.time() - t0)
    except Exception:
        return 0.0


if __name__ == "__main__":
    sys.exit(main())
